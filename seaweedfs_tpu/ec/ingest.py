"""Inline-EC ingest — encode-on-write stripe building + GF-linear delta
parity updates (the ROADMAP's write-heavy workload opener).

Today's EC path is a warm-storage conversion: `write_ec_files` batch-
encodes a sealed volume, so heavy ingest traffic never touches the
encoder. This module turns the encoder into a continuously-busy service:
an `InlineStripeBuilder` accumulates stripe state per OPEN volume,
encoding each large row through the exact `_encode_rows` staging-ring
pipeline the warm path runs as soon as the append-only .dat has grown
past it (a row is provably a LARGE row of the final layout once the file
strictly exceeds the row after it — the warm layout rule is monotone in
file size), so a volume crossing its seal threshold is BORN EC'd:
`seal()` only encodes the not-yet-covered large rows plus the small-row
tail and emits `.ec00-.ec13`/`.eci` byte-identical to what
`write_ec_files` would produce on the same sealed volume.

Overwrites landing inside already-encoded rows (the .dat is append-only
except for the superblock rewrite, compaction — which invalidates the
state wholesale — and direct patch tooling) are folded in as DELTA
parity updates: GF(2^8) linearity makes parity a sum of per-data-shard
terms, so parity' = parity ⊕ G_col·(old ⊕ new) on just the touched byte
columns (`Encoder.parity_delta`, golden `gf8.gf_delta_parity`) — a
rank-1 update moving O(changed) bytes instead of re-encoding the stripe,
the linearity family the XOR-EC program-optimization literature in
PAPERS.md builds on and PR 7's trace projections already exploit.

Crash safety: all progress is journaled in a `<base>.ecp` sidecar (JSON
lines, flush+fsync per record — the `kernel_sweep --out` discipline; a
torn tail line from a crash mid-append is ignored on read). Shard bytes
live in `<base>.ecNN.inp` partials invisible to `find_local_shards`/
`Store.load`. The ordering contract: row bytes are fsync'd BEFORE their
`rows` watermark record, so resume can always truncate the partials back
to the watermark; overwrites write an `ow` INTENT record (old+new bytes)
before mutating the .dat, then one absolute-bytes `delta` record per
patched segment, then `ow-done` — replay is idempotent and a crash at
any point is recoverable by comparing the .dat against the intent. A
state the journal cannot vouch for (geometry drift, truncated partials,
un-resolvable intent) makes `resume` return None and the seal falls back
to the warm conversion — inline EC is an amortization, never an
availability or integrity trade.
"""

from __future__ import annotations

import base64
import json
import os
import threading
from typing import Callable, Optional

import numpy as np

from seaweedfs_tpu.ec import stripe
from seaweedfs_tpu.obs import trace as trace_mod
from seaweedfs_tpu.ec.constants import (
    DATA_SHARDS_COUNT,
    EC_BUFFER_SIZE,
    TOTAL_SHARDS_COUNT,
)
from seaweedfs_tpu.utils import config

#: journal (stripe-progress sidecar) and in-progress shard-partial suffixes.
#: Neither matches the `.ecNN`/`.ecx` discovery globs, so a crashed inline
#: encode can never be mistaken for a complete shard set.
JOURNAL_EXT = ".ecp"
PART_SUFFIX = ".inp"

_JOURNAL_VERSION = 1

#: "no spread decision made yet" sentinel for IngestManager._spreads —
#: distinct from None, which latches "factory declined/failed: stay local"
_SPREAD_UNSET = object()


def journal_path(base_file_name: str) -> str:
    return base_file_name + JOURNAL_EXT


def part_path(base_file_name: str, shard_id: int) -> str:
    return stripe.shard_file_name(base_file_name, shard_id) + PART_SUFFIX


def _append_record(f, record: dict) -> None:
    """One JSON line, flush+fsync'd as it lands (kernel_sweep --out
    discipline): a kill leaves at worst a torn tail, never a half-trusted
    record."""
    f.write((json.dumps(record, separators=(",", ":")) + "\n").encode())
    f.flush()
    os.fsync(f.fileno())


def read_journal(base_file_name: str) -> list[dict]:
    """Every parseable record in order. A torn tail (crash mid-append)
    terminates the read — the partial line and anything after it is not
    evidence."""
    return _read_journal_prefix(base_file_name)[0]


def _read_journal_prefix(base_file_name: str) -> tuple[list[dict], int]:
    """(records, valid_bytes): the parseable record prefix and how many
    bytes of the file it spans. A resume MUST truncate the journal to
    `valid_bytes` before appending — records written after a torn
    fragment would be concatenated onto it and become invisible to every
    later recovery."""
    try:
        with open(journal_path(base_file_name), "rb") as f:
            raw = f.read()
    except OSError:
        return [], 0
    records: list[dict] = []
    valid = 0
    pos = 0
    for line in raw.split(b"\n"):
        end = pos + len(line) + 1  # +1: the split-off newline
        if end > len(raw):
            break  # no trailing newline = torn by definition, even if it
            # happens to parse — records and truncation point must agree
        if line.strip():
            try:
                rec = json.loads(line)
            except ValueError:
                break  # torn tail: ignore it and stop trusting what follows
            if isinstance(rec, dict):
                records.append(rec)
        valid = end
        pos = end
    return records, valid


def _b64(b) -> str:
    return base64.b64encode(bytes(b)).decode()


def _fsync_all(handles) -> None:
    """flush + fsync a set of shard handles CONCURRENTLY: a watermark
    flush syncs all 14 partials, and on latency-bound storage serial
    fsync pays 14 round-trips where parallel pays ~one. Ordering is
    unchanged — every fsync still completes before the caller journals
    the watermark record."""
    handles = list(handles)
    if not handles:
        return
    if len(handles) == 1:
        handles[0].flush()
        os.fsync(handles[0].fileno())
        return
    from concurrent.futures import ThreadPoolExecutor

    def sync(h):
        h.flush()
        os.fsync(h.fileno())

    with ThreadPoolExecutor(
        max_workers=min(8, len(handles)), thread_name_prefix="inline-ec-fsync"
    ) as ex:
        for fut in [ex.submit(sync, h) for h in handles]:
            fut.result()


def _dat_revision(base_file_name: str) -> Optional[int]:
    """The volume superblock's compact_revision (bytes 4:6 of the .dat),
    or None when unreadable. Compaction bumps it while rewriting every
    needle offset — a journal pinned to the old revision must NEVER
    resume over the compacted file (the partials encode deleted bytes).
    The superblock's replica-placement byte is NOT part of this pin: the
    configure-replication delta path legitimately rewrites it in place."""
    try:
        with open(base_file_name + ".dat", "rb") as f:
            raw = f.read(6)
    except OSError:
        return None
    if len(raw) < 6:
        return None
    return int.from_bytes(raw[4:6], "big")


class InlineStripeBuilder:
    """Incremental encode-on-write stripe state for ONE open volume.

    `poll()` encodes newly-completed large rows (cheap no-op otherwise),
    `overwrite()` folds an in-place .dat change into the encoded rows as
    a journaled delta parity update, `seal()` finalizes the byte-exact
    warm-equivalent shard set, `abort()` drops the partials. All public
    methods are serialized by one lock; any failure marks the builder
    `broken` so the seal path falls back to the warm conversion instead
    of trusting half-updated parity."""

    def __init__(
        self,
        base_file_name: str,
        encoder,
        large_block_size: int,
        small_block_size: int,
        buffer_size: int = EC_BUFFER_SIZE,
        max_batch_bytes: int = 64 * 1024 * 1024,
        pipeline_depth: Optional[int] = None,
        delta_enabled: Optional[bool] = None,
        _resume: bool = False,
    ):
        self.base = base_file_name
        self._enc = encoder
        self.large = int(large_block_size)
        self.small = int(small_block_size)
        self._buffer = int(buffer_size)
        self._max_batch = int(max_batch_bytes)
        self._depth = pipeline_depth
        self._delta_enabled = (
            config.env("WEEDTPU_INLINE_EC_DELTA")
            if delta_enabled is None
            else bool(delta_enabled)
        )
        self.rows_done = 0
        #: rows covered by the last fsync'd watermark record — durability is
        #: BATCHED: polls encode eagerly but fsync the partials + journal
        #: the watermark only every `_durable_batch` bytes of rows (per-row
        #: fsync of 15 files would dominate small-row amortized cost; a
        #: crash merely re-encodes the undurable tail from the .dat, which
        #: is the durable source of truth either way)
        self._durable_rows = 0
        self._durable_batch = 64 * 1024 * 1024
        self.crcs = [0] * TOTAL_SHARDS_COUNT
        self.crc_valid = True
        self.broken = False
        self.closed = False
        self.resumed = _resume
        self.delta_stats = {"updates": 0, "changed_bytes": 0, "accounted_bytes": 0}
        self._lock = threading.RLock()
        #: serializes journal appends across the poll/overwrite threads and
        #: the async watermark flusher (lock order: _lock before
        #: _journal_lock, everywhere)
        self._journal_lock = threading.Lock()
        self._parts: list = []
        self._journal = None
        #: per-poll overhead killers (ROADMAP inline-EC follow-up 1): the
        #: staging ring persists ACROSS polls (stripe._encode_rows reuses
        #: it via ring_cache instead of re-allocating fresh buffers whose
        #: first touch page-faults every poll), the .dat read handle
        #: stays open for the builder's life (the file is append-only;
        #: compaction discards the whole builder), and watermark fsyncs
        #: run on a flusher thread so durability batching never stalls
        #: the encode lane
        self._ring_cache: dict = {}
        self._dat = None
        self._flusher = None  # lazy single-worker executor
        #: optional parity-spread hook (shard_id, pos, length) — set by the
        #: IngestManager when WEEDTPU_INLINE_EC_SPREAD is on, so a delta
        #: patch below the shipped watermark marks the target range dirty
        self.on_parity_patch = None
        #: rows already handed to the flusher — the threshold check must
        #: not re-submit a job per poll while one is still fsyncing (each
        #: stale job would re-fsync all 14 partials before noticing)
        self._flush_submitted_rows = 0
        if not _resume:
            try:
                self._parts = [
                    open(part_path(base_file_name, s), "w+b")  # weedlint: ignore[open-no-ctx] builder-lifetime partials, closed in abort()/seal()
                    for s in range(TOTAL_SHARDS_COUNT)
                ]
                # weedlint: ignore[open-no-ctx] builder-lifetime journal handle, closed in abort()/seal()
                self._journal = open(journal_path(base_file_name), "wb")
                self._journal_append(self._begin_record())
            except BaseException:
                self._close_handles()
                raise

    def _begin_record(self) -> dict:
        return {
            "kind": "begin",
            "version": _JOURNAL_VERSION,
            "large": self.large,
            "small": self.small,
            "data_shards": self._enc.data_shards,
            "parity_shards": self._enc.parity_shards,
            "matrix_kind": self._enc.matrix_kind,
            # pins this journal to THIS generation of the .dat: compaction
            # bumps the revision, so a stale journal surviving a restart
            # can never resume over the offset-shifted rewrite
            "dat_rev": _dat_revision(self.base),
        }

    # -- geometry ------------------------------------------------------------

    @property
    def _large_row(self) -> int:
        return self.large * DATA_SHARDS_COUNT

    def encoded_limit(self) -> int:
        """First .dat byte NOT covered by an encoded row — overwrites below
        this need a delta update, appends above it just wait for poll."""
        return self.rows_done * self._large_row

    def _layout(self, dat_size: int) -> tuple[int, int]:
        """(n_large, n_small) — delegated to `stripe.stripe_layout`, the
        ONE layout definition the byte-identity contract hangs on."""
        return stripe.stripe_layout(dat_size, self.large, self.small)

    def _available_rows(self, dat_size: int) -> int:
        """Large rows of the FINAL layout already fully determined: row k is
        large iff dat_size > (k+1) rows — and file growth only ever adds
        rows, so once a row qualifies it stays qualified (monotone)."""
        return max(0, -(-dat_size // self._large_row) - 1)

    # -- incremental encode ---------------------------------------------------

    def poll(self) -> int:
        """Encode any newly-completed large rows through the staging-ring
        pipeline; returns rows encoded (0 = nothing new, the per-PUT fast
        path: one getsize and out)."""
        with self._lock:
            if self.broken or self.closed:
                return 0
            try:
                dat_size = os.path.getsize(self.base + ".dat")
            except OSError:
                return 0
            n_new = self._available_rows(dat_size) - self.rows_done
            if n_new <= 0:
                return 0
            try:
                self._encode_large(n_new)
            except BaseException:
                self.broken = True
                raise
            return n_new

    def _dat_handle(self):
        """The builder-lifetime .dat read handle: the file is append-only
        for the builder's life (compaction/delete discard the builder),
        so one open amortizes over every poll instead of paying an
        open/close per poll."""
        if self._dat is None:
            # weedlint: ignore[open-no-ctx] builder-lifetime read handle, closed in abort()/seal()
            self._dat = open(self.base + ".dat", "rb")
        return self._dat

    def _encode_large(self, n_rows: int) -> None:
        """Encode `n_rows` large rows starting at the progress cursor.
        Durability is batched: shard bytes are fsync'd BEFORE their
        watermark record whenever a flush happens (resume truncates the
        partials back to the last durable watermark), but the flush
        itself fires only per `_durable_batch` bytes — a crash costs
        re-encoding the undurable tail, never trusting unfsync'd bytes."""
        f = self._dat_handle()
        for h in self._parts:
            h.seek(self.rows_done * self.large)
        with trace_mod.start("ingest.encode", klass="ingest") as sp:
            if sp is not None:
                sp.annotate(rows=n_rows, row_start=self.rows_done)
            self._encode_large_rows(f, n_rows)
        self.rows_done += n_rows
        undurable = self.rows_done - max(self._durable_rows, self._flush_submitted_rows)
        if undurable * self._large_row >= self._durable_batch:
            # async: the encode lane keeps rolling while the flusher
            # thread makes the batch durable (fsync-before-record
            # ordering preserved inside the job)
            self._flush_watermark(wait=False)
        try:
            from seaweedfs_tpu import stats

            stats.InlineEcRows.inc(n_rows)
            stats.InlineEcBytes.inc(n_rows * self._large_row)
        except Exception:  # noqa: BLE001 — metrics must never break ingest
            pass

    def _encode_large_rows(self, f, n_rows: int) -> None:
        stripe._encode_rows(
            f,
            self._enc,
            self._parts,
            self.rows_done * self._large_row,
            self.large,
            n_rows,
            self._buffer,
            # right-size the staging ring to the work actually available:
            # an ingest poll usually encodes ONE row (so steady-state polls
            # hit the SAME cached ring geometry every time), and allocating
            # the warm path's full batch budget per poll would dominate the
            # amortized cost with dead buffer churn
            min(self._max_batch, max(self._buffer * DATA_SHARDS_COUNT,
                                     n_rows * self._large_row)),
            self._depth,
            self.crcs,
            ring_cache=self._ring_cache,
        )

    def _journal_append(self, record: dict) -> None:
        with self._journal_lock:
            _append_record(self._journal, record)

    def _flush_watermark(self, wait: bool = True) -> None:
        """fsync every partial, THEN journal the watermark: a durable
        `rows` record always describes bytes that are already on disk.

        wait=False hands the whole job (fsync + record) to the builder's
        flusher thread — the poll path's durability batching then
        overlaps the next rows' encode instead of stalling it. The
        ordering contract is unchanged: the job fsyncs before it
        journals, and a job whose snapshot fell behind a newer durable
        watermark (a later sync flush won the race) appends nothing."""
        if self._durable_rows == self.rows_done:
            return
        rows = self.rows_done
        crcs = [int(c) for c in self.crcs] if self.crc_valid else None
        if wait:
            _fsync_all(self._parts)
            self._journal_append({"kind": "rows", "rows": rows, "crcs": crcs})
            self._durable_rows = rows
            self._flush_submitted_rows = max(self._flush_submitted_rows, rows)
            return
        if self._flusher is None:
            from concurrent.futures import ThreadPoolExecutor

            self._flusher = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="inline-ec-flush"
            )
        self._flush_submitted_rows = rows
        self._flusher.submit(self._flush_job, list(self._parts), rows, crcs)

    def _flush_job(self, parts: list, rows: int, crcs) -> None:
        """One async watermark: fsync the snapshot's handles (outside the
        builder lock — encodes keep rolling), then journal the record iff
        the builder is still live and this watermark is still the newest.
        A seal/abort racing the fsync just makes it a no-op: their own
        fsync covers the bytes, and closed handles raise harmlessly."""
        try:
            _fsync_all(parts)
        except Exception:  # noqa: BLE001 — closed mid-seal/abort: skip
            return
        with self._lock:
            if self.closed or self.broken or self._journal is None:
                return
            if rows <= self._durable_rows:
                return  # a newer sync flush already covered these rows
            try:
                self._journal_append({"kind": "rows", "rows": rows, "crcs": crcs})
            except Exception:  # noqa: BLE001 — a missed watermark only
                # costs resume re-encoding from the previous one
                return
            self._durable_rows = rows

    # -- delta parity updates -------------------------------------------------

    def overwrite(
        self,
        offset: int,
        old,
        new,
        mutate: Optional[Callable[[], None]] = None,
    ) -> int:
        """Fold an in-place .dat overwrite [offset, offset+len) into the
        stripe. `mutate` (when given) performs the actual .dat write and
        runs AFTER the intent record is durable, so a crash at any point
        is resolvable from the journal (see module docstring). Returns
        bytes patched inside already-encoded rows (0 = nothing encoded
        was touched, or deltas are disabled — in which case a touched
        encoded range marks the builder broken → warm fallback)."""
        old_b = bytes(old)
        new_b = bytes(new)
        if len(old_b) != len(new_b):
            raise ValueError(
                f"old/new overwrite blocks disagree on length: "
                f"{len(old_b)} vs {len(new_b)}"
            )
        with self._lock:
            if self.closed:
                # a seal closed this builder between the caller's lookup and
                # now: the caller's mutation must STILL land — refusing here
                # would silently drop e.g. a replication-configure rewrite
                if mutate is not None:
                    mutate()
                return 0
            touches = (
                not self.broken
                and offset < self.encoded_limit()
                and old_b != new_b
            )

            def run_mutate() -> None:
                """The caller's .dat write. When it fails with encoded rows
                at stake, the .dat may be PARTIALLY rewritten — the builder
                can no longer vouch for its parity, so break it before
                letting the caller's error propagate (their RPC must fail
                exactly like the non-inline path's would)."""
                if mutate is None:
                    return
                try:
                    mutate()
                except BaseException:
                    if touches:
                        self.broken = True
                    raise

            if not touches:
                run_mutate()
                return 0
            if not self._delta_enabled:
                # parity for the touched rows goes stale and deltas are
                # off: the only honest option is the warm re-encode
                self.broken = True
                run_mutate()
                return 0
            try:
                # deltas must land ABOVE a durable watermark: resume replays
                # them against rows it can actually truncate back to
                self._flush_watermark()
                self._journal_append(
                    {"kind": "ow", "off": int(offset), "old": _b64(old_b), "new": _b64(new_b)},
                )
            except BaseException:
                # journaling failed: the CALLER's mutation must still land
                # (it was promised); the builder just can't vouch for its
                # parity anymore
                self.broken = True
                run_mutate()
                return 0
            run_mutate()
            try:
                patched = self._update_encoded(
                    offset,
                    np.frombuffer(old_b, dtype=np.uint8),
                    np.frombuffer(new_b, dtype=np.uint8),
                )
                self._journal_append({"kind": "ow-done"})
            except BaseException:  # noqa: BLE001 — the mutation LANDED and
                # the intent record preserves it; a failed delta just means
                # this builder can no longer vouch for parity (warm
                # fallback at seal). The caller's operation succeeded, so
                # nothing propagates.
                self.broken = True
                return 0
            return patched

    def _update_encoded(
        self,
        offset: int,
        old: np.ndarray,
        new: np.ndarray,
        skip: Optional[set] = None,
    ) -> int:
        """Apply delta parity updates for the encoded part of the range,
        segment by (row, data shard) block. `skip` lists (pos, shard)
        segments already restored by journal replay (their absolute bytes
        are on disk; re-deriving a delta for them would double-apply)."""
        limit = self.encoded_limit()
        end = min(offset + old.size, limit)
        patched = 0
        p = offset
        while p < end:
            row, q = divmod(p, self._large_row)
            d, col = divmod(q, self.large)
            seg = min(self.large - col, end - p)
            o = old[p - offset : p - offset + seg]
            n = new[p - offset : p - offset + seg]
            pos = row * self.large + col
            if (skip is None or (pos, d) not in skip) and not np.array_equal(o, n):
                self._apply_delta(pos, d, o, n)
                patched += seg
            p += seg
        if patched:
            self.crc_valid = False
            # an overwrite changed encoded bytes for this base: decoded
            # intervals cached before the delta describe the OLD contents
            # and must never serve another read (PR 16 no-stale-bytes rule)
            from seaweedfs_tpu.ec import read_planner as read_planner_mod

            read_planner_mod.CACHE.invalidate_volume(self.base)
            self.delta_stats["updates"] += 1
            self.delta_stats["changed_bytes"] += patched
            # accounting for the small-write gate: old+new data bytes in,
            # one data-range write, and a read-modify-write per parity
            # shard — the bytes a delta computes/moves, vs a full stripe
            # re-encode's dat_size + parity writes
            accounted = patched * (2 + 2 * self._enc.parity_shards)
            self.delta_stats["accounted_bytes"] += accounted
            try:
                from seaweedfs_tpu import stats

                stats.InlineEcDeltaUpdates.inc()
                stats.InlineEcDeltaBytes.inc(accounted)
            except Exception:  # noqa: BLE001
                pass
        return patched

    def _apply_delta(self, pos: int, d: int, old_seg: np.ndarray, new_seg: np.ndarray) -> None:
        """One (row, data shard) segment: journal the absolute post-state
        bytes (idempotent redo), then rewrite the data range and XOR the
        GF delta into each parity shard's touched range."""
        dp = self._enc.parity_delta(d, old_seg, new_seg)  # (P, seg)
        writes: dict[int, bytes] = {d: new_seg.tobytes()}
        seg = old_seg.size
        for pi in range(self._enc.parity_shards):
            h = self._parts[DATA_SHARDS_COUNT + pi]
            h.seek(pos)
            cur = h.read(seg)
            if len(cur) != seg:
                raise IOError(
                    f"{self.base}: parity partial {DATA_SHARDS_COUNT + pi} "
                    f"truncated at {pos}+{seg}"
                )
            writes[DATA_SHARDS_COUNT + pi] = (
                np.frombuffer(cur, dtype=np.uint8) ^ dp[pi]
            ).tobytes()
        self._journal_append(
            {
                "kind": "delta",
                "pos": int(pos),
                "d": int(d),
                "writes": {str(s): _b64(b) for s, b in writes.items()},
            },
        )
        for s, b in writes.items():
            h = self._parts[s]
            h.seek(pos)
            h.write(b)
            h.flush()
            os.fsync(h.fileno())
        if self.on_parity_patch is not None:
            for s, b in writes.items():
                if s >= DATA_SHARDS_COUNT:
                    try:
                        self.on_parity_patch(s, pos, len(b))
                    except Exception:  # noqa: BLE001 — spread is best-effort
                        pass

    # -- seal / abort ---------------------------------------------------------

    def seal(self) -> dict:
        """Finalize `.ec00-.ec13` + `.eci` byte-identical to warm
        `write_ec_files` on the same sealed .dat: encode the remaining
        large rows and the small-row tail, recompute shard CRCs when a
        delta invalidated the streamed ones, fsync, and rename the
        partials into place. Returns the amortization accounting."""
        with trace_mod.ensure("ingest.seal", klass="ingest"), self._lock:
            trace_mod.annotate(rows_inline=self.rows_done)
            if self.broken or self.closed:
                raise IOError(f"{self.base}: inline stripe state unusable")
            dat_size = os.path.getsize(self.base + ".dat")
            n_large, n_small = self._layout(dat_size)
            rows_inline = self.rows_done
            if self.rows_done > n_large:
                raise IOError(
                    f"{self.base}: encoded {self.rows_done} large rows but the "
                    f"final layout has {n_large} — .dat shrank?"
                )
            try:
                if n_large > self.rows_done:
                    self._encode_large(n_large - self.rows_done)
                if n_small:
                    f = self._dat_handle()
                    for h in self._parts:
                        h.seek(0, os.SEEK_END)
                    stripe._encode_rows(
                        f,
                        self._enc,
                        self._parts,
                        n_large * self._large_row,
                        self.small,
                        n_small,
                        min(self._buffer, self.small),
                        self._max_batch,
                        self._depth,
                        self.crcs,
                        ring_cache=self._ring_cache,
                    )
                if not self.crc_valid:
                    self._recompute_crcs()
                _fsync_all(self._parts)
                for h in self._parts:
                    h.close()
                self._parts = []
                for s in range(TOTAL_SHARDS_COUNT):
                    os.replace(
                        part_path(self.base, s), stripe.shard_file_name(self.base, s)
                    )
                stripe.write_ec_info(
                    self.base, self.large, self.small, dat_size, shard_crcs=self.crcs
                )
            except BaseException:
                self.broken = True
                raise
            if self._journal is not None:
                self._journal.close()
                self._journal = None
            if self._dat is not None:
                self._dat.close()
                self._dat = None
            if self._flusher is not None:
                self._flusher.shutdown(wait=False)
                self._flusher = None
            self._ring_cache.clear()
            try:
                os.unlink(journal_path(self.base))
            except OSError:
                pass
            self.closed = True
            return {
                "rows_inline": rows_inline,
                "rows_total": n_large,
                "small_rows": n_small,
                "delta_updates": self.delta_stats["updates"],
                "delta_bytes": self.delta_stats["accounted_bytes"],
            }

    def _recompute_crcs(self) -> None:
        """Delta patches mutate shard bytes in place; CRC32 of a stream is
        not patchable, so after any delta the per-shard CRCs are recomputed
        in one pass over the finalized partials — the .eci then records the
        same values a warm encode of the final .dat would."""
        import zlib

        for s, h in enumerate(self._parts):
            h.flush()
            h.seek(0)
            crc = 0
            while True:
                chunk = h.read(4 * 1024 * 1024)
                if not chunk:
                    break
                crc = zlib.crc32(chunk, crc)
            self.crcs[s] = crc
        self.crc_valid = True

    def _close_handles(self) -> None:
        for h in self._parts:
            try:
                h.close()
            except OSError:
                pass
        self._parts = []
        if self._journal is not None:
            try:
                self._journal.close()
            except OSError:
                pass
            self._journal = None
        if self._dat is not None:
            try:
                self._dat.close()
            except OSError:
                pass
            self._dat = None
        if self._flusher is not None:
            self._flusher.shutdown(wait=False)
            self._flusher = None
        self._ring_cache.clear()

    def abort(self) -> None:
        """Drop the in-progress state: close handles, unlink partials and
        the journal. The .dat is untouched — a later warm conversion (or a
        fresh builder) rebuilds everything from it."""
        with self._lock:
            self.closed = True
            self._close_handles()
            for s in range(TOTAL_SHARDS_COUNT):
                try:
                    os.unlink(part_path(self.base, s))
                except OSError:
                    pass
            try:
                os.unlink(journal_path(self.base))
            except OSError:
                pass

    # -- crash recovery -------------------------------------------------------

    @classmethod
    def resume(
        cls,
        base_file_name: str,
        encoder,
        large_block_size: int,
        small_block_size: int,
        **kwargs,
    ) -> Optional["InlineStripeBuilder"]:
        """Rebuild a builder from the journaled sidecar after a crash.
        Returns None whenever the on-disk state cannot be vouched for
        (missing/foreign journal, geometry or codec drift, truncated
        partials, unresolvable overwrite intent) — the caller then aborts
        the partials and the seal falls back to the warm conversion."""
        records, journal_valid = _read_journal_prefix(base_file_name)
        if not records or records[0].get("kind") != "begin":
            return None
        head = records[0]
        if head.get("version") != _JOURNAL_VERSION:
            return None
        if (
            int(head.get("large", -1)) != int(large_block_size)
            or int(head.get("small", -1)) != int(small_block_size)
            or int(head.get("data_shards", -1)) != encoder.data_shards
            or int(head.get("parity_shards", -1)) != encoder.parity_shards
            or head.get("matrix_kind") != encoder.matrix_kind
        ):
            return None
        if head.get("dat_rev") != _dat_revision(base_file_name):
            # the .dat was compacted (or replaced) since the journal began:
            # every encoded row maps to the OLD offsets — not resumable
            return None
        rows, crcs, any_delta = 0, [0] * TOTAL_SHARDS_COUNT, False
        deltas: list[dict] = []
        pending: Optional[dict] = None
        pending_deltas: list[dict] = []
        for rec in records[1:]:
            kind = rec.get("kind")
            if kind == "rows":
                rows = int(rec.get("rows", 0))
                rc = rec.get("crcs")
                if isinstance(rc, list) and len(rc) == TOTAL_SHARDS_COUNT:
                    crcs = [int(c) for c in rc]
                else:
                    any_delta = True  # crcs went stale before this record
            elif kind == "delta":
                any_delta = True
                deltas.append(rec)
                if pending is not None:
                    pending_deltas.append(rec)
            elif kind == "ow":
                any_delta = True
                pending = rec
                pending_deltas = []
            elif kind == "ow-done":
                pending = None
                pending_deltas = []
        expected = rows * int(large_block_size)
        for s in range(TOTAL_SHARDS_COUNT):
            try:
                size = os.path.getsize(part_path(base_file_name, s))
            except OSError:
                return None  # a partial vanished: the set is not trustworthy
            if size < expected:
                return None  # journal ahead of the files: fsync contract broken
        b = cls(
            base_file_name,
            encoder,
            large_block_size,
            small_block_size,
            _resume=True,
            **kwargs,
        )
        try:
            b._parts = [
                open(part_path(base_file_name, s), "r+b")  # weedlint: ignore[open-no-ctx] builder-lifetime partials, closed in abort()/seal()
                for s in range(TOTAL_SHARDS_COUNT)
            ]
            b.rows_done = rows
            b._durable_rows = rows
            b.crcs = crcs
            # CRC provenance contract: the watermark's streamed CRCs are
            # exact ONLY when nothing mutated shard bytes in place since
            # they were recorded. Any delta record, any pending overwrite
            # intent (its resolution below may patch further segments),
            # or a watermark that dropped its crcs (crc_valid was already
            # False at record time — folded into any_delta above) forces
            # seal() to RECOMPUTE the .eci CRCs from the finalized
            # partials: the sealed record must describe the bytes on
            # disk, never a stale stream fold that a later fsck/scrub
            # would flag as corruption on a perfectly healthy volume.
            b.crc_valid = not any_delta and pending is None
            for h in b._parts:
                h.truncate(expected)  # drop rows past the durable watermark
            # redo: delta records carry absolute post-state bytes, so
            # replay is idempotent whatever subset already hit the disk
            for rec in deltas:
                pos = int(rec.get("pos", -1))
                for s_str, b64v in (rec.get("writes") or {}).items():
                    s = int(s_str)
                    data = base64.b64decode(b64v)
                    if 0 <= s < TOTAL_SHARDS_COUNT and 0 <= pos and pos + len(data) <= expected:
                        h = b._parts[s]
                        h.seek(pos)
                        h.write(data)
            # drop any torn tail BEFORE appending: records written after a
            # torn fragment would concatenate onto it and become invisible
            # to every later recovery
            with open(journal_path(base_file_name), "r+b") as jf:
                jf.truncate(journal_valid)
            # journal reopens BEFORE intent resolution: resolving may append
            # fresh delta records for segments the crash never reached
            # weedlint: ignore[open-no-ctx] builder-lifetime journal handle, closed in abort()/seal()
            b._journal = open(journal_path(base_file_name), "ab")
            if pending is not None:
                if not b._resolve_pending(pending, pending_deltas):
                    b._close_handles()
                    return None
                b._journal_append({"kind": "ow-done"})
            _fsync_all(b._parts)
        except BaseException:
            b._close_handles()
            raise
        return b

    def _resolve_pending(self, pending: dict, replayed: list[dict]) -> bool:
        """A crash mid-overwrite left an intent without its `ow-done`.
        Compare the .dat against the recorded old/new bytes to learn how
        far the mutation got, then finish the delta for exactly the
        segments no replayed record already restored. False = the .dat
        matches neither state — someone else mutated it; not recoverable."""
        try:
            off = int(pending["off"])
            old = base64.b64decode(pending["old"])
            new = base64.b64decode(pending["new"])
        except (KeyError, ValueError):
            return False
        try:
            with open(self.base + ".dat", "rb") as f:
                f.seek(off)
                cur = f.read(len(new))
        except OSError:
            return False
        if cur == old:
            return True  # crash before the mutate: nothing to fold in
        if cur != new:
            return False  # unknown mutation: the intent cannot vouch for it
        covered = {
            (int(rec.get("pos", -1)), int(rec.get("d", -1))) for rec in replayed
        }
        self._update_encoded(
            off,
            np.frombuffer(old, dtype=np.uint8),
            np.frombuffer(new, dtype=np.uint8),
            skip=covered,
        )
        return True


class IngestManager:
    """Per-server inline-EC policy + builder registry.

    `on_write(vid)` is the write-path hook (cheap when no new row is
    complete); `overwrite(vid, ...)` routes in-place .dat mutations
    through the journaled delta path; `seal_volume(vid, base)` finalizes
    inline state (resuming a crashed builder from its journal first) and
    falls back to the warm `write_ec_files` whenever the inline state
    cannot be vouched for; `discard(vid)` invalidates state a compaction
    or volume delete made stale."""

    def __init__(
        self,
        store,
        seal_bytes: Optional[int] = None,
        delta_enabled: Optional[bool] = None,
        large_block_size: Optional[int] = None,
        small_block_size: Optional[int] = None,
        buffer_size: int = EC_BUFFER_SIZE,
        max_batch_bytes: int = 64 * 1024 * 1024,
        seal_trigger: Optional[Callable[[int], None]] = None,
        spread_factory: Optional[Callable] = None,
    ):
        self.store = store
        #: WEEDTPU_INLINE_EC_SPREAD: `spread_factory(vid, base) ->
        #: SpreadSession | None` supplied by the volume server; sessions
        #: tee each parity shard's encoded rows to its eventual holder so
        #: seal cut-over only ships the tail
        self._spread_factory = spread_factory
        self._spreads: dict[int, object] = {}
        self.seal_bytes = (
            config.env("WEEDTPU_INLINE_EC_SEAL_BYTES")
            if seal_bytes is None
            else int(seal_bytes)
        )
        self.delta_enabled = (
            config.env("WEEDTPU_INLINE_EC_DELTA")
            if delta_enabled is None
            else bool(delta_enabled)
        )
        self.large = (
            config.env("WEEDTPU_INLINE_EC_LARGE_BLOCK")
            if large_block_size is None
            else int(large_block_size)
        )
        self.small = (
            config.env("WEEDTPU_INLINE_EC_SMALL_BLOCK")
            if small_block_size is None
            else int(small_block_size)
        )
        self._buffer = buffer_size
        self._max_batch = max_batch_bytes
        self._seal_trigger = seal_trigger
        self._builders: dict[int, InlineStripeBuilder] = {}
        self._sealing: set[int] = set()
        self._lock = threading.Lock()
        # encode runs OFF the write-ack path: on_write only marks the
        # volume dirty (plus the cheap threshold check); one worker thread
        # drains dirty volumes and polls their builders. A PUT must never
        # pay a stripe row's encode — at production geometry one large row
        # is 10 GiB, and even a fresh builder over an existing volume
        # (whole-backlog encode) just keeps the worker busy, not a client.
        self._dirty: set[int] = set()
        self._cv = threading.Condition(self._lock)
        self._stopped = False
        self._worker = threading.Thread(
            target=self._poll_loop, daemon=True, name="inline-ec-encoder"
        )
        self._worker.start()

    def _builder_kwargs(self) -> dict:
        return {
            "buffer_size": self._buffer,
            "max_batch_bytes": self._max_batch,
            "delta_enabled": self.delta_enabled,
        }

    def builder_for(self, vid: int, base: str) -> Optional[InlineStripeBuilder]:
        """The volume's live builder, resuming a journaled one (crash
        recovery) before starting fresh. None while a seal owns the
        volume's stripe state — the fence is re-checked HERE, under the
        same lock seal_volume raises it with, so a racing write can never
        resume/create a builder over partials being finalized."""
        with self._lock:
            if vid in self._sealing:
                return None
            b = self._builders.get(vid)
            if b is not None and not b.closed:
                return b
            if os.path.exists(journal_path(base)):
                b = InlineStripeBuilder.resume(
                    base, self.store.encoder, self.large, self.small,
                    **self._builder_kwargs(),
                )
                if b is None:
                    # un-vouchable leftovers: clear them before starting over
                    _cleanup_partials(base)
            else:
                b = None
            if b is None:
                b = InlineStripeBuilder(
                    base, self.store.encoder, self.large, self.small,
                    **self._builder_kwargs(),
                )
            self._builders[vid] = b
            return b

    def on_write(self, vid: int) -> None:
        """Post-append hook: ensure the volume has a builder, mark it dirty
        for the encoder worker, and trigger the auto-seal when the .dat
        crossed the threshold. O(handful of syscalls) — the actual row
        encode happens on the worker thread, never in the write ack.
        Never raises into the write path — a failed poll marks the
        builder broken and the seal will fall back to warm."""
        v = self.store.get_volume(vid)
        if v is None or v.read_only or getattr(v, "tiered", False):
            return
        try:
            b = self.builder_for(vid, v.base_path)
        except Exception:  # noqa: BLE001 — inline EC must not fail ingest
            b = None
        if b is not None:
            with self._cv:
                self._dirty.add(vid)
                self._cv.notify()
        if self.seal_bytes and self._seal_trigger is not None:
            try:
                size = os.path.getsize(v.dat_path)
            except OSError:
                return
            if size >= self.seal_bytes:
                with self._lock:
                    if vid in self._sealing:
                        return
                    self._sealing.add(vid)
                threading.Thread(
                    target=self._seal_trigger, args=(vid,), daemon=True,
                    name=f"inline-ec-seal-{vid}",
                ).start()

    def _poll_loop(self) -> None:
        """The encoder worker: drain dirty volumes, poll their builders.
        Per-volume failures mark that builder broken (warm fallback at
        seal) and never stop the loop."""
        while True:
            with self._cv:
                while not self._dirty and not self._stopped:
                    self._cv.wait()
                if self._stopped:
                    return
                vid = self._dirty.pop()
                b = self._builders.get(vid)
            if b is None or b.closed:
                continue
            try:
                b.poll()
            except Exception:  # noqa: BLE001 — builder marked broken
                continue
            self._spread_poll(vid, b)

    def _spread_poll(self, vid: int, b: InlineStripeBuilder) -> None:
        """Tee newly-encoded parity rows to the volume's spread session
        (created lazily from the factory; a failed creation latches off
        for this volume — spreading must never become a retry storm on
        the encoder worker)."""
        if self._spread_factory is None or b.broken:
            return
        with self._lock:
            session = self._spreads.get(vid, _SPREAD_UNSET)
        if session is _SPREAD_UNSET:
            try:
                session = self._spread_factory(vid, b.base)
            except Exception:  # noqa: BLE001 — no plan, no spread
                session = None
            with self._lock:
                self._spreads[vid] = session
            if session is not None:
                b.on_parity_patch = session.note_patch
        if session is None:
            return
        try:
            session.poll(b.rows_done)
        except Exception:  # noqa: BLE001 — session marks itself broken
            pass

    def take_spread(self, vid: int):
        """Hand the volume's spread session to the seal path (and stop
        polling it). None when spreading never started for this volume."""
        with self._lock:
            session = self._spreads.pop(vid, None)
        return None if session is _SPREAD_UNSET else session

    def close(self) -> None:
        """Stop the encoder worker (server shutdown). Builders keep their
        journaled state on disk — the next process resumes or falls back."""
        with self._cv:
            self._stopped = True
            self._cv.notify_all()
        self._worker.join(timeout=5.0)

    def seal_failed(self, vid: int) -> None:
        """Re-arm the auto-seal trigger after a failed attempt."""
        with self._lock:
            self._sealing.discard(vid)

    def overwrite(
        self,
        vid: int,
        offset: int,
        old,
        new,
        mutate: Optional[Callable[[], None]] = None,
    ) -> int:
        """In-place .dat mutation hook (e.g. the superblock rewrite):
        journal + delta-update through the volume's builder when one is
        live OR journaled on disk (a restart must not let a mutation slip
        past the stripe state it left behind — builder_for resumes it
        first), plain mutate otherwise."""
        v = self.store.get_volume(vid)
        with self._lock:
            b = self._builders.get(vid)
        if (
            (b is None or b.closed)
            and v is not None
            and os.path.exists(journal_path(v.base_path))
        ):
            try:  # journaled state from before a restart: resume it or the
                # mutation would slip past the partials it left behind
                b = self.builder_for(vid, v.base_path)
            except Exception:  # noqa: BLE001 — unusable state: plain mutate
                b = None
        if b is None or b.closed:
            if mutate is not None:
                mutate()
            return 0
        # no catch here: the builder swallows its OWN failures (marking
        # itself broken for the warm fallback) and lets only the caller's
        # mutate errors propagate — an RPC whose .dat write failed must
        # fail exactly like it would without inline EC
        return b.overwrite(offset, old, new, mutate=mutate)

    def seal_volume(self, vid: int, base: str, **encode_kwargs) -> dict:
        """Finalize the volume's shard set: inline state when usable
        (resumed from the journal after a crash), warm `write_ec_files`
        otherwise. Returns {"mode": inline|resumed|warm, ...accounting}."""
        with self._lock:
            # fence out concurrent write-path polling for the whole seal:
            # a fresh builder spawned mid-seal would truncate the partials
            # being renamed into place (builder_for re-checks this set
            # under the same lock)
            self._sealing.add(vid)
            b = self._builders.pop(vid, None)
        try:
            if (b is None or b.closed) and os.path.exists(journal_path(base)):
                try:
                    b = InlineStripeBuilder.resume(
                        base, self.store.encoder, self.large, self.small,
                        **self._builder_kwargs(),
                    )
                except Exception:  # noqa: BLE001 — unreadable state: warm path
                    b = None
            info: dict = {"mode": "warm"}
            if b is not None and not b.closed:
                if not b.broken:
                    try:
                        b.poll()  # rows completed since the last write
                        info.update(b.seal())
                        info["mode"] = "resumed" if b.resumed else "inline"
                    except Exception:  # noqa: BLE001 — fall back to warm
                        b.abort()
                        info = {"mode": "warm"}
                else:
                    b.abort()
            if info["mode"] == "warm":
                _cleanup_partials(base)
                stripe.write_ec_files(
                    base,
                    large_block_size=encode_kwargs.pop("large_block_size", self.large),
                    small_block_size=encode_kwargs.pop("small_block_size", self.small),
                    encoder=self.store.encoder,
                    **encode_kwargs,
                )
        finally:
            # the fence exists only for the seal's duration — leaving it up
            # after a FAILED seal would silently disable inline polling and
            # auto-seal for this volume forever (successful seals leave the
            # volume read-only, which gates on_write by itself)
            with self._lock:
                self._sealing.discard(vid)
        try:
            from seaweedfs_tpu import stats

            stats.InlineEcSeals.labels(info["mode"]).inc()
        except Exception:  # noqa: BLE001
            pass
        return info

    def discard(self, vid: int, base: Optional[str] = None) -> None:
        """Invalidate inline state whose source .dat is being rewritten or
        removed (compaction, volume delete, tier move). `base` (when the
        caller still knows it) also scrubs the ON-DISK journal/partials —
        a server restart empties the builder dict, but a stale journal
        left on disk would otherwise wait to be resumed over the rewritten
        file (the dat_rev pin refuses it, but dead files must not linger)."""
        with self._lock:
            b = self._builders.pop(vid, None)
            self._sealing.discard(vid)
            session = self._spreads.pop(vid, None)
        if session is not None and session is not _SPREAD_UNSET:
            try:
                session.abort()  # scrub the remote partials too
            except Exception:  # noqa: BLE001 — dead peers keep only .inp litter
                pass
        if b is not None:
            b.abort()
        if base is None and b is not None:
            base = b.base
        if base is not None:
            _cleanup_partials(base)


def _cleanup_partials(base: str) -> None:
    for s in range(TOTAL_SHARDS_COUNT):
        try:
            os.unlink(part_path(base, s))
        except OSError:
            pass
    try:
        os.unlink(journal_path(base))
    except OSError:
        pass
