"""Fleet-scale repair scheduler — the master-side brain that turns
per-shard healing (PR 3/7 remote rebuild, PR 10 scrub) into a cluster
that survives a node, then a rack.

A dead volume server leaves HUNDREDS of stripes each short a shard, and
the ORDER they are repaired in decides data-loss risk ("Practical
Considerations in Repairing Reed-Solomon Codes", PAPERS.md): a stripe
missing 2 shards is one failure from data loss while a 1-missing stripe
still has slack, so 2-missing repairs strictly first. This module owns:

  - `RepairQueue` — a redundancy-ranked priority queue: stripes order by
    (missing shards DESC, stripe bytes DESC, single-domain exposure
    DESC, vid). Re-ranking mid-storm (a second holder of a queued stripe
    dies) is a lazy-invalidation push: the stale heap entry is skipped
    on pop.
  - `RepairScheduler` — death detection (reaped nodes, heartbeat-silent
    holders, peer-unreachable reports from volume servers), full-registry
    scans that enumerate every under-replicated stripe, a correlation
    settle window so a rack's second node dying 200 ms after its first
    is ranked as ONE event, and a paced dispatch loop that batches many
    volumes' rebuilds into `VolumeEcShardsRebuildBatch` RPCs (one fused
    decode dispatch per missing-signature group on the target — the
    PR 9 residual) under a cluster-wide `WEEDTPU_REPAIR_MAX_INFLIGHT`
    budget, backing off exponentially on 503/RESOURCE_EXHAUSTED so the
    existing rebuild admission lane keeps foreground SLOs intact while
    a repair storm runs.

Repair traffic is still the holders' PR 6 admission lane: every slab or
projection stream the batch rebuild opens takes a rebuild token on the
holder serving it; the scheduler's budget bounds how many such rebuild
RPCs are in flight cluster-wide on top.
"""

from __future__ import annotations

import heapq
import threading
import time
from collections import deque
from typing import Optional

import grpc

from seaweedfs_tpu import rpc, stats
from seaweedfs_tpu.ec import placement
from seaweedfs_tpu.ec.constants import DATA_SHARDS_COUNT, TOTAL_SHARDS_COUNT
from seaweedfs_tpu.pb import VOLUME_SERVICE
from seaweedfs_tpu.utils import config


class RepairQueue:
    """Thread-safe redundancy-ranked priority queue of stripes.

    Priority tuple: (-missing, -stripe_bytes, -exposure, vid) — Python's
    min-heap then pops the most-missing (least-redundant) stripe first,
    big stripes before small at equal redundancy, higher single-domain
    exposure before lower. `update` re-ranks by pushing a fresh entry;
    stale entries are skipped on pop (lazy invalidation — the classic
    decrease-key-free heap)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._heap: list[tuple] = []
        self._prio: dict[int, tuple] = {}
        self._order = 0

    @staticmethod
    def priority(missing: int, stripe_bytes: int, exposure: int, vid: int) -> tuple:
        return (-int(missing), -int(stripe_bytes), -int(exposure), int(vid))

    def update(self, vid: int, prio: tuple) -> bool:
        """Insert or re-rank; True when the entry changed (new or moved)."""
        with self._lock:
            if self._prio.get(vid) == prio:
                return False
            self._prio[vid] = prio
            self._order += 1
            heapq.heappush(self._heap, (prio, self._order, vid))
            return True

    def discard(self, vid: int) -> None:
        with self._lock:
            self._prio.pop(vid, None)

    def pop(self) -> Optional[tuple[int, tuple]]:
        """(vid, priority) of the most urgent live entry, or None."""
        with self._lock:
            while self._heap:
                prio, _, vid = heapq.heappop(self._heap)
                if self._prio.get(vid) == prio:
                    del self._prio[vid]
                    return vid, prio
            return None

    def peek_class(self) -> Optional[int]:
        """Missing-count of the head entry (None when empty)."""
        with self._lock:
            while self._heap:
                prio, _, vid = self._heap[0]
                if self._prio.get(vid) == prio:
                    return -prio[0]
                heapq.heappop(self._heap)
            return None

    def members(self) -> dict[int, tuple]:
        with self._lock:
            return dict(self._prio)

    def __len__(self) -> int:
        with self._lock:
            return len(self._prio)


class RepairScheduler:
    """Master-side mass-rebuild scheduler (see module docstring).

    Lifecycle: `start()` spawns the scan + dispatch threads; `stop()`
    joins them. Only the raft leader dispatches (followers keep their
    queue warm from their own soft-state topology, so a failover resumes
    mid-storm). All knobs are registered repair env entries (see
    utils/config.py), overridable per-instance for tests."""

    EVENT_LOG = 1024  # bounded dispatch/outcome history for RepairStatus
    REPORT_TTL = 30.0  # seconds an un-renewed peer-unreachable report stands

    def __init__(
        self,
        master,
        *,
        max_inflight: Optional[int] = None,
        batch: Optional[int] = None,
        scan_interval: Optional[float] = None,
        settle: Optional[float] = None,
        dead_after: Optional[float] = None,
        backoff_base: Optional[float] = None,
        cap_override: Optional[int] = None,
    ) -> None:
        self.master = master
        self.max_inflight = (
            config.env("WEEDTPU_REPAIR_MAX_INFLIGHT")
            if max_inflight is None
            else max(1, int(max_inflight))
        )
        self.batch = (
            config.env("WEEDTPU_REPAIR_BATCH") if batch is None else max(1, int(batch))
        )
        self.scan_interval = (
            config.env("WEEDTPU_REPAIR_SCAN_S")
            if scan_interval is None
            else float(scan_interval)
        )
        self.settle = (
            config.env("WEEDTPU_REPAIR_SETTLE_S") if settle is None else float(settle)
        )
        self.dead_after = (
            config.env("WEEDTPU_REPAIR_DEAD_S")
            if dead_after is None
            else float(dead_after)
        )
        self.backoff_base = (
            config.env("WEEDTPU_REPAIR_BACKOFF")
            if backoff_base is None
            else float(backoff_base)
        )
        self.cap_override = (
            config.env("WEEDTPU_PLACEMENT_MAX_PER_DOMAIN")
            if cap_override is None
            else int(cap_override)
        )
        self.queue = RepairQueue()
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._gate = threading.BoundedSemaphore(self.max_inflight)
        self._inflight: set[int] = set()
        self._mu = threading.Lock()
        self._events: deque = deque(maxlen=self.EVENT_LOG)
        self._seq = 0
        self._settle_until = 0.0
        #: peer-unreachable reports: suspect grpc addr -> {reporter url:
        #: monotonic ts}. Entries age out after REPORT_TTL unless renewed
        #: by a fresh heartbeat report — a reporter that recovered simply
        #: stops naming the peer and the suspicion evaporates.
        self._reports: dict[str, dict[str, float]] = {}
        #: suspects already confirmed dead — repeated reports about them
        #: must NOT keep extending the settle window (that would starve
        #: dispatch for as long as heartbeats keep naming the corpse)
        self._confirmed: set[str] = set()
        #: stripes already logged as unrecoverable (missing > m) — one
        #: LOST event per episode, not one per scan
        self._lost: set[int] = set()
        self._not_before: dict[int, float] = {}
        self._backoff: dict[int, float] = {}
        self._hist: dict[str, int] = {}
        #: per-dispatch occupancy records (bounded, newest last): how many
        #: volumes and signature groups each batch carried, the fused
        #: dispatch count the target reported, the in-batch block order,
        #: and the dispatch->response wall — the storm post-mortem data
        #: RepairStatus serves
        self._batches: deque = deque(maxlen=256)
        self._fused_volumes_total = 0
        self._threads: list[threading.Thread] = []

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        self._threads = [
            threading.Thread(target=self._scan_loop, daemon=True, name="repair-scan"),
            threading.Thread(
                target=self._dispatch_loop, daemon=True, name="repair-dispatch"
            ),
        ]
        for t in self._threads:
            t.start()

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        for t in self._threads:
            t.join(timeout=5.0)

    # -- event log -----------------------------------------------------------

    def _event(self, state: str, vid: int, missing: int, target: str = "", detail: str = "") -> int:
        with self._mu:
            self._seq += 1
            seq = self._seq
            self._events.append(
                {
                    "seq": seq,
                    "volume_id": int(vid),
                    "missing": int(missing),
                    "state": state,
                    "target": target,
                    "t": time.monotonic(),
                    "detail": detail[:200],
                }
            )
        return seq

    # -- death signals -------------------------------------------------------

    def kick(self, reason: str = "") -> None:
        """A death/coverage signal landed: open (or extend) the settle
        window so correlated failures rank together, then wake the
        loops. Cheap and lock-light — callable from heartbeat ingest."""
        with self._mu:
            self._settle_until = time.monotonic() + self.settle
        self._wake.set()

    def note_reports(self, reporter_url: str, peers) -> None:
        """Fold one heartbeat's peer-unreachable report in. A peer is
        treated as dead-for-repair only when it ALSO stopped
        heartbeating (`dead_after`) — one slow reporter must not declare
        a healthy node dead — but confirmed reports skip the topology
        reaper's much longer DEAD_NODE window."""
        if not peers:
            return
        newly_confirmed = False
        now = time.monotonic()
        with self._mu:
            for addr in peers:
                self._reports.setdefault(str(addr), {})[reporter_url] = now
            self._prune_reports(now)
        topo = self.master.topology
        with topo._lock:
            by_grpc = {n.grpc_address: n for n in topo.nodes.values()}
            dead_now = {
                str(addr)
                for addr in peers
                if (node := by_grpc.get(str(addr))) is None
                or (now - node.last_seen) >= self.dead_after
            }
        with self._mu:
            fresh = dead_now - self._confirmed
            self._confirmed |= fresh
            for addr in map(str, peers):
                # a suspect that is heartbeating again un-confirms, so a
                # LATER real death of the same addr kicks afresh
                if addr not in dead_now:
                    self._confirmed.discard(addr)
            newly_confirmed = bool(fresh)
        if newly_confirmed:
            self.kick("peer-unreachable report confirmed")

    def _prune_reports(self, now: float) -> None:
        """Drop aged-out report entries (caller holds _mu)."""
        for addr in list(self._reports):
            live = {
                r: t
                for r, t in self._reports[addr].items()
                if now - t < self.REPORT_TTL
            }
            if live:
                self._reports[addr] = live
            else:
                del self._reports[addr]
                self._confirmed.discard(addr)

    def _holder_live(self, node, now: float) -> bool:
        """Is this topology node a live holder for repair purposes?
        Reported-unreachable peers die at `dead_after` of heartbeat
        silence; unreported ones at 4x (a long GC pause alone must not
        trigger a mass rebuild)."""
        age = now - node.last_seen
        if age < self.dead_after:
            return True
        with self._mu:
            self._prune_reports(now)
            reported = bool(self._reports.get(node.grpc_address))
        return not reported and age < max(60.0, 4.0 * self.dead_after)

    # -- enumeration ---------------------------------------------------------

    def scan(self) -> int:
        """Enumerate every under-replicated stripe from the master's EC
        registry and (re-)rank it. Returns how many entries changed —
        the storm signal the settle window dampens.

        Confirmed-dead holders (peer-reported AND heartbeat-silent, or
        silent past the unreported bound) are EXPELLED from the topology
        first — the read-path-evidence-driven fast reaper. Without it
        the corpse's shards keep answering "present" to every consumer
        (lookup routing, rebuild survivor choice, this very scan) until
        the slow DEAD_NODE reaper lands. A resurrected node re-registers
        wholesale on its next heartbeat."""
        topo = self.master.topology
        now = time.monotonic()
        with topo._lock:
            expelled = [
                u for u, n in topo.nodes.items()
                if not self._holder_live(n, now)
            ]
        for u in expelled:
            topo.unregister_node(u)
        with topo._lock:
            live = {
                u: n for u, n in topo.nodes.items() if self._holder_live(n, now)
            }
            registry = {
                vid: {sid: set(urls) for sid, urls in m.items()}
                for vid, m in topo.ec_locations.items()
            }
            geometry = dict(getattr(topo, "ec_geometry", {}))
            domains = {
                u: (n.data_center, n.rack) for u, n in topo.nodes.items()
            }
        changed = 0
        hist: dict[str, int] = {}
        seen = set()
        for vid, shard_map in registry.items():
            holders = {
                sid: [u for u in urls if u in live]
                for sid, urls in shard_map.items()
            }
            present = {sid for sid, urls in holders.items() if urls}
            geo = geometry.get(vid) or {}
            data = int(geo.get("data_shards") or 0) or DATA_SHARDS_COUNT
            total = int(geo.get("total_shards") or 0) or TOTAL_SHARDS_COUNT
            shard_size = int(geo.get("shard_size") or 0)
            parity = max(1, total - data)
            missing = [s for s in range(total) if s not in present]
            hist[str(min(len(missing), parity + 1))] = (
                hist.get(str(min(len(missing), parity + 1)), 0) + 1
            )
            seen.add(vid)
            if not missing:
                self.queue.discard(vid)
                self._lost.discard(vid)
                continue
            if len(missing) > parity:
                if vid not in self._lost:
                    self._lost.add(vid)
                    self._event(
                        "lost", vid, len(missing),
                        detail=f"only {len(present)} shards survive, need {data}",
                    )
                self.queue.discard(vid)
                continue
            self._lost.discard(vid)
            with self._mu:
                if vid in self._inflight:
                    continue  # already being repaired; re-ranked on completion
            exposure = placement.domain_exposure(holders, domains)
            prio = RepairQueue.priority(
                len(missing), shard_size * data, exposure, vid
            )
            if self.queue.update(vid, prio):
                changed += 1
        # entries for vids that left the registry entirely (deleted)
        for vid in list(self.queue.members()):
            if vid not in seen:
                self.queue.discard(vid)
        with self._mu:
            self._hist = hist
        stats.RepairQueueDepth.set(len(self.queue))
        return changed

    # -- loops ---------------------------------------------------------------

    def _scan_loop(self) -> None:
        while not self._stop.is_set():
            woke = self._wake.wait(timeout=self.scan_interval)
            if self._stop.is_set():
                return
            if woke:
                self._wake.clear()
            try:
                if self.scan():
                    self._wake.set()  # new work: dispatch promptly
            except Exception:  # noqa: BLE001 — the scheduler must never die
                pass

    def _maintenance_idle(self) -> bool:
        """Defer the storm while an operator holds the cluster admin lock
        — exactly the auto-vacuum's discipline: a mass rebuild racing an
        ec.convert/balance would interleave on the same volumes."""
        locks = getattr(self.master, "_admin_locks", None)
        mu = getattr(self.master, "_admin_lock_mu", None)
        if locks is None or mu is None:
            return True
        now = time.monotonic()
        with mu:
            return not any(exp > now for _, exp, _ in locks.values())

    def _dispatch_loop(self) -> None:
        while not self._stop.is_set():
            if not len(self.queue):
                self._wake.wait(timeout=self.scan_interval)
                self._wake.clear()
                continue
            now = time.monotonic()
            with self._mu:
                settle_left = self._settle_until - now
            if settle_left > 0:
                # correlation window: a rack's nodes die milliseconds
                # apart but their heartbeats silence staggers — ranking
                # before the dust settles would start 1-missing repairs
                # that a moment later should have been 2-missing
                self._stop.wait(min(settle_left, 0.25))
                continue
            if not self.master.is_leader or not self._maintenance_idle():
                self._stop.wait(1.0)
                continue
            # acquire the inflight slot BEFORE popping: while all slots
            # are busy nothing is popped or inflight-marked, so work that
            # arrives (or re-ranks) during the wait is seen at its fresh
            # priority — popping first would dispatch a stale batch the
            # moment a slot frees, ahead of newer 2-missing stripes
            self._gate.acquire()
            if self._stop.is_set():
                self._gate.release()
                return
            with self._mu:
                settle_open = self._settle_until > time.monotonic()
            if settle_open:
                self._gate.release()
                continue  # loop re-enters the settle wait
            job = self._next_batch()
            if job is None:
                self._gate.release()
                self._stop.wait(0.25)
                continue
            threading.Thread(
                target=self._run_batch, args=job, daemon=True,
                name="repair-batch",
            ).start()

    # -- batch assembly ------------------------------------------------------

    def _topology_view(self):
        topo = self.master.topology
        now = time.monotonic()
        with topo._lock:
            nodes = [
                {
                    "url": u,
                    "grpc": n.grpc_address,
                    "data_center": n.data_center,
                    "rack": n.rack,
                    "ec_load": sum(
                        b.shard_id_count() for b in n.ec_shards.values()
                    ),
                }
                for u, n in topo.nodes.items()
                if self._holder_live(n, now)
            ]
            registry = {
                vid: {sid: sorted(urls) for sid, urls in m.items()}
                for vid, m in topo.ec_locations.items()
            }
            domains = {u: (n.data_center, n.rack) for u, n in topo.nodes.items()}
            geometry = dict(getattr(topo, "ec_geometry", {}))
            collections = dict(topo.ec_collections)
        return nodes, registry, domains, geometry, collections

    def _next_batch(self):
        """Pop the head stripe, choose its domain-compliant rebuild
        target, and greedily add queued stripes — ACROSS priority
        classes — that the same target can legally host.  One RPC then
        carries the whole settle-window cohort, and the target fuses
        every signature group into one block-diagonal decode dispatch.
        Members are added in priority order, so 2-before-1 survives as
        the batch's BLOCK order rather than as separate rounds."""
        head = self.queue.pop()
        if head is None:
            return None
        vid, prio = head
        now = time.monotonic()
        nb = self._not_before.get(vid, 0.0)
        if nb > now:
            self.queue.update(vid, prio)  # still backing off: rotate
            if len(self.queue) == 1:
                self._stop.wait(min(nb - now, 0.5))
            return None
        nodes, registry, domains, geometry, collections = self._topology_view()
        if not nodes:
            self.queue.update(vid, prio)
            self._stop.wait(1.0)
            return None

        def target_for(v: int, candidates=None):
            holders = registry.get(v) or {}
            geo = geometry.get(v) or {}
            data = int(geo.get("data_shards") or 0) or DATA_SHARDS_COUNT
            total = int(geo.get("total_shards") or 0) or TOTAL_SHARDS_COUNT
            present = {s for s, urls in holders.items() if urls}
            missing = [s for s in range(total) if s not in present]
            return placement.pick_rebuild_target(
                nodes if candidates is None else candidates,
                holders, domains, missing, max(1, total - data),
                cap_override=self.cap_override,
                strict=candidates is not None,
            ), len(missing)

        target, n_missing = target_for(vid)
        if n_missing == 0:
            # healed between rank and dispatch (a holder came back, a
            # peer's rebuild landed): nothing to send — and dispatching
            # a no-op batch would churn the event log forever
            return None
        if target is None:
            self.queue.update(vid, prio)
            self._stop.wait(1.0)
            return None
        batch = [(vid, prio, n_missing)]
        if self.batch > 1:
            for v2, p2 in sorted(
                self.queue.members().items(), key=lambda kv: kv[1]
            ):
                if len(batch) >= self.batch:
                    break
                if self._not_before.get(v2, 0.0) > now:
                    continue
                # the head's target joins the batch whenever it can
                # LEGALLY host this stripe's missing shards — requiring
                # each stripe's independently-ranked best target to
                # coincide would split the cohort by load-balance noise
                t2, m2 = target_for(v2, candidates=[target])
                if m2 == 0:
                    self.queue.discard(v2)  # healed: nothing to batch
                    continue
                if t2 is not None:
                    self.queue.discard(v2)
                    batch.append((v2, p2, m2))
        with self._mu:
            for v, _, _ in batch:
                self._inflight.add(v)
        stats.RepairInflight.set(len(self._inflight))
        vols = [
            {"volume_id": v, "collection": collections.get(v, "")}
            for v, _, _ in batch
        ]
        return (target, batch, vols)

    # -- dispatch ------------------------------------------------------------

    def _run_batch(self, target: dict, batch: list, vols: list) -> None:
        addr = target["grpc"]
        seqs = {}
        n_missing_of = {v: n for v, _, n in batch}
        for v, prio, n_missing in batch:
            seqs[v] = self._event("dispatched", v, n_missing, target=addr)
            stats.RepairDispatch.labels(str(n_missing)).inc()
        t_dispatch = time.monotonic()
        try:
            try:
                with rpc.RpcClient(addr) as c:
                    resp = c.call(
                        VOLUME_SERVICE,
                        "VolumeEcShardsRebuildBatch",
                        {"volumes": vols},
                        timeout=600,
                    )
            except grpc.RpcError as e:
                transient = e.code() in (
                    grpc.StatusCode.RESOURCE_EXHAUSTED,
                    grpc.StatusCode.UNAVAILABLE,
                )
                self._requeue(batch, str(e), transient=transient)
                return
            except Exception as e:  # noqa: BLE001 — transport-level failure
                self._requeue(batch, str(e), transient=True)
                return
            wall_s = time.monotonic() - t_dispatch
            # the RPC mounts rebuilt shards before returning, so this wall
            # IS dispatch->mount for every volume the batch carried
            block_order = [int(v) for v in resp.get("block_order", [])]
            record = {
                "target": addr,
                "volumes": len(batch),
                "signature_groups": int(resp.get("signature_groups", 0)),
                "dispatch_groups": int(resp.get("dispatch_groups", 0)),
                "block_order": block_order,
                "block_missing": [n_missing_of.get(v, 0) for v in block_order],
                "wall_s": round(wall_s, 6),
                "t": time.monotonic(),
            }
            with self._mu:
                self._batches.append(record)
                self._fused_volumes_total += int(resp.get("volumes_fused", 0))
            stats.RepairFusedVolumes.inc(int(resp.get("volumes_fused", 0)))
            stats.RepairDispatchGroups.set(int(resp.get("dispatch_groups", 0)))
            results = {
                int(r.get("volume_id", -1)): r for r in resp.get("results", [])
            }
            ok, failed = [], []
            for v, prio, n_missing in batch:
                r = results.get(v) or {}
                if r.get("error"):
                    failed.append((v, prio, n_missing, r["error"]))
                else:
                    ok.append((v, n_missing, r))
            for v, n_missing, r in ok:
                self._event(
                    "done", v, n_missing, target=addr,
                    detail=f"rebuilt {r.get('rebuilt_shard_ids')}",
                )
                with self._mu:
                    self._backoff.pop(v, None)
                    self._not_before.pop(v, None)
            for v, prio, n_missing, err in failed:
                lowered = err.lower()
                transient = (
                    "resource_exhausted" in lowered
                    or "unavailable" in lowered
                    or "503" in lowered
                )
                self._requeue(
                    [(v, prio, n_missing)], err, transient=transient
                )
        finally:
            with self._mu:
                for v, _, _ in batch:
                    self._inflight.discard(v)
            stats.RepairInflight.set(len(self._inflight))
            self._gate.release()
            self._wake.set()  # completions may unblock the next class

    def _requeue(self, batch: list, err: str, transient: bool) -> None:
        """Exponential per-stripe backoff: 503/RESOURCE_EXHAUSTED (the
        admission lane pushing back) and transport failures retry
        calmly; the stripe keeps its rank so it still beats less-urgent
        work once the backoff expires."""
        now = time.monotonic()
        for v, prio, n_missing in batch:
            with self._mu:
                cur = self._backoff.get(v, self.backoff_base)
                self._backoff[v] = min(cur * 2.0, 12.0 * self.backoff_base)
                self._not_before[v] = now + cur
            state = "backoff" if transient else "failed"
            self._event(state, v, n_missing, detail=err)
            stats.RepairBackoff.inc()
            self.queue.update(v, prio)

    # -- status --------------------------------------------------------------

    def status(self) -> dict:
        """The RepairStatus RPC payload: queue depth, inflight, the
        redundancy histogram from the last scan, current placement
        violations, suspects, and the recent event log."""
        _, registry, domains, geometry, _ = self._topology_view()
        violations: list[str] = []
        for vid, holders in sorted(registry.items()):
            geo = geometry.get(vid) or {}
            data = int(geo.get("data_shards") or 0) or DATA_SHARDS_COUNT
            total = int(geo.get("total_shards") or 0) or TOTAL_SHARDS_COUNT
            for dom, sids in placement.stripe_violations(
                holders, domains, max(1, total - data),
                cap_override=self.cap_override,
            ):
                violations.append(
                    f"vid={vid} domain={dom[0]}/{dom[1]} holds "
                    f"{len(sids)}>{placement.max_per_domain(max(1, total - data), self.cap_override)} "
                    f"shards {sids}"
                )
        stats.PlacementViolations.set(len(violations))
        now = time.monotonic()
        with self._mu:
            events = [
                {
                    "seq": e["seq"],
                    "volume_id": e["volume_id"],
                    "missing": e["missing"],
                    "state": e["state"],
                    "target": e["target"],
                    "age_s": round(now - e["t"], 3),
                    "detail": e["detail"],
                }
                for e in self._events
            ]
            hist = dict(self._hist)
            suspects = sorted(
                a for a, reporters in self._reports.items() if reporters
            )
            inflight = len(self._inflight)
            batches = [
                {
                    "target": b["target"],
                    "volumes": b["volumes"],
                    "signature_groups": b["signature_groups"],
                    "dispatch_groups": b["dispatch_groups"],
                    "block_order": list(b["block_order"]),
                    "block_missing": list(b["block_missing"]),
                    "wall_s": b["wall_s"],
                    "age_s": round(now - b["t"], 3),
                }
                for b in self._batches
            ]
            fused_total = self._fused_volumes_total
        return {
            "enabled": True,
            "queue_depth": len(self.queue),
            "inflight": inflight,
            "redundancy_histogram": hist,
            "violations": violations,
            "events": events,
            "suspects": suspects,
            "batches": batches,
            "fused_volumes_total": fused_total,
        }
