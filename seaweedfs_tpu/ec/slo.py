"""SLO instrumentation for the serving path: an HDR-style latency
recorder and the committed-artifact report format weedload and chaos_soak
write (`artifacts/SLO_r*.json`, the latency sibling of `BENCH_r*.json`).

The recorder is open-loop-friendly: observations are bucketed into
geometrically-spaced cells (~5% relative precision from 0.1 ms to 2 min,
one int per cell) so recording costs O(1) with no per-sample allocation
and quantiles stay exact to the bucket width no matter how skewed the
distribution — the property HdrHistogram popularized and a p99-under-
chaos measurement needs (a reservoir would subsample exactly the tail
the SLO is about). Samples are keyed by (phase, klass): phase is WHEN
(steady, chaos), klass is WHAT (healthy vs degraded traffic), so one run
yields the healthy-vs-degraded comparison the stated SLO is defined
over: degraded p99 < FACTOR x healthy p99.
"""

from __future__ import annotations

import bisect
import json
import os
import threading
import time
from typing import Optional

_MIN = 1e-4  # 0.1 ms: below this, bucket 0 (scheduler noise, not signal)
_MAX = 120.0  # 2 min: beyond any deadline in the system
_GROWTH = 1.05  # ~5% relative quantile error


def _bounds() -> list[float]:
    out = [_MIN]
    while out[-1] < _MAX:
        out.append(out[-1] * _GROWTH)
    return out


BUCKET_BOUNDS: tuple[float, ...] = tuple(_bounds())


class _Cell:
    # every mutation is a read-modify-write (counts[i]+=1, sum+=s): a
    # per-cell lock keeps 64 recording threads from dropping samples —
    # the artifact's counts must be exact even if the quantiles are
    # bucket-precision
    __slots__ = ("counts", "total", "sum", "errors", "max", "lock")

    def __init__(self) -> None:
        self.counts = [0] * (len(BUCKET_BOUNDS) + 1)
        self.total = 0
        self.sum = 0.0
        self.errors = 0
        self.max = 0.0
        self.lock = threading.Lock()

    def observe(self, seconds: float) -> None:
        i = bisect.bisect_left(BUCKET_BOUNDS, seconds)
        with self.lock:
            self.counts[i] += 1
            self.total += 1
            self.sum += seconds
            self.max = max(self.max, seconds)

    def inc_error(self) -> None:
        with self.lock:
            self.errors += 1

    def merge(self, other: "_Cell") -> None:
        with other.lock:
            counts, total, sum_ = list(other.counts), other.total, other.sum
            errors, max_ = other.errors, other.max
        for i, c in enumerate(counts):
            self.counts[i] += c
        self.total += total
        self.sum += sum_
        self.errors += errors
        self.max = max(self.max, max_)

    def to_dict(self) -> dict:
        """Wire form for cross-process merging (weedload --procs workers
        ship their recorders back as JSON). Bucket bounds are code-level
        constants, so counts alone round-trip exactly."""
        with self.lock:
            return {
                "counts": list(self.counts),
                "total": self.total,
                "sum": self.sum,
                "errors": self.errors,
                "max": self.max,
            }

    @classmethod
    def from_dict(cls, d: dict) -> "_Cell":
        cell = cls()
        counts = list(d["counts"])
        if len(counts) != len(cell.counts):
            raise ValueError(
                f"bucket count mismatch: {len(counts)} != {len(cell.counts)} "
                "(recorder serialized by a different code version?)"
            )
        cell.counts = counts
        cell.total = int(d["total"])
        cell.sum = float(d["sum"])
        cell.errors = int(d["errors"])
        cell.max = float(d["max"])
        return cell

    def _quantile(self, q: float) -> float:
        """Value at quantile `q` (caller holds the lock or owns the cell),
        reported as the matching bucket's upper bound (conservative:
        never under-reports a tail)."""
        if self.total == 0:
            return 0.0
        rank = q * self.total
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= rank and c:
                return BUCKET_BOUNDS[i] if i < len(BUCKET_BOUNDS) else self.max
        return self.max

    def quantile(self, q: float) -> float:
        with self.lock:
            return self._quantile(q)

    def summary(self) -> dict:
        with self.lock:
            return {
                "count": self.total,
                "errors": self.errors,
                "mean": round(self.sum / self.total, 6) if self.total else 0.0,
                "p50": round(self._quantile(0.50), 6),
                "p95": round(self._quantile(0.95), 6),
                "p99": round(self._quantile(0.99), 6),
                "max": round(self.max, 6),
            }


class LatencyRecorder:
    """Thread-safe (phase, klass)-keyed latency histogram set."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._cells: dict[tuple[str, str], _Cell] = {}

    def _cell(self, phase: str, klass: str) -> _Cell:
        key = (phase, klass)
        with self._lock:
            cell = self._cells.get(key)
            if cell is None:
                cell = self._cells[key] = _Cell()
            return cell

    def observe(self, phase: str, klass: str, seconds: float) -> None:
        self._cell(phase, klass).observe(seconds)

    def error(self, phase: str, klass: str) -> None:
        self._cell(phase, klass).inc_error()

    def merged(self, klass: str) -> _Cell:
        """One cell folding every phase's samples for `klass` — the
        whole-run healthy/degraded distributions the SLO compares."""
        out = _Cell()
        with self._lock:
            items = list(self._cells.items())
        for (_, k), cell in items:
            if k == klass:
                out.merge(cell)
        return out

    def to_dict(self) -> dict:
        """{"phase\\tklass": cell-dict} — what a weedload generator worker
        writes to its result file; the driver folds every worker's dict
        into one recorder with merge_dict."""
        with self._lock:
            items = list(self._cells.items())
        return {f"{phase}\t{klass}": cell.to_dict() for (phase, klass), cell in items}

    def merge_dict(self, d: dict) -> None:
        for key, cell_dict in d.items():
            phase, klass = key.split("\t", 1)
            self._cell(phase, klass).merge(_Cell.from_dict(cell_dict))

    def phases(self) -> dict:
        """{phase: {klass: summary}} — the per-phase artifact section."""
        out: dict[str, dict] = {}
        with self._lock:
            items = list(self._cells.items())
        for (phase, klass), cell in sorted(items):
            out.setdefault(phase, {})[klass] = cell.summary()
        return out


def slo_verdict(
    recorder: LatencyRecorder,
    factor: float = 5.0,
    healthy: str = "healthy",
    degraded: str = "degraded",
    min_samples: int = 20,
    max_error_rate: float = 0.10,
) -> dict:
    """The stated SLO: degraded p99 < `factor` x healthy p99, judged over
    the whole run (all phases merged). Below `min_samples` on either side
    the verdict is not evidence and says so instead of vacuously passing.
    Errors gate the verdict too: a quantile computed over the few reads
    that SUCCEEDED certifies nothing when most degraded reads failed, so
    either class exceeding `max_error_rate` fails the SLO outright."""
    h = recorder.merged(healthy).summary()
    d = recorder.merged(degraded).summary()
    enough = h["count"] >= min_samples and d["count"] >= min_samples
    # None, not inf: the artifact must stay strict JSON
    ratio = round(d["p99"] / h["p99"], 3) if h["p99"] > 0 else None

    def _err_rate(s: dict) -> float:
        attempts = s["count"] + s["errors"]
        return (s["errors"] / attempts) if attempts else 0.0

    h_err, d_err = _err_rate(h), _err_rate(d)
    return {
        "target": f"degraded_p99 < {factor} x healthy_p99",
        "factor": factor,
        "healthy_p99": h["p99"],
        "degraded_p99": d["p99"],
        "ratio": ratio,
        "healthy_error_rate": round(h_err, 4),
        "degraded_error_rate": round(d_err, 4),
        "max_error_rate": max_error_rate,
        "enough_samples": enough,
        "ok": bool(
            enough
            and ratio is not None
            and ratio < factor
            and h_err <= max_error_rate
            and d_err <= max_error_rate
        ),
    }


def assemble_report(
    recorder: LatencyRecorder,
    workload: dict,
    chaos: Optional[dict] = None,
    knobs: Optional[dict] = None,
    counters: Optional[dict] = None,
    lost: Optional[list] = None,
    slo_factor: float = 5.0,
    classes: tuple = ("healthy", "degraded"),
    corruption: Optional[dict] = None,
) -> dict:
    """The SLO_r*.json schema (committed-artifact format, BENCH_r* sibling):
    workload parameters, per-phase per-class quantiles, whole-run
    aggregates, the SLO verdict, the chaos ledger, and zero-loss evidence.
    `classes` lists the traffic classes folded into the `overall` section
    — healthy/degraded always (the SLO comparison), plus e.g. `put` when
    the run offered write traffic (weedload --put-fraction). `corruption`
    (weedload --corrupt) is the fault-injection ledger: every injected
    bit-flip/truncation/deletion with its healed verdict — `ok` then also
    demands all_healed (an unhealed injection is as disqualifying as a
    lost byte)."""
    merged_classes = tuple(dict.fromkeys(("healthy", "degraded") + tuple(classes)))
    report = {
        "when": time.strftime("%FT%TZ", time.gmtime()),
        "kind": "slo",
        "workload": workload,
        "chaos": chaos or {},
        "phases": recorder.phases(),
        "overall": {
            klass: recorder.merged(klass).summary() for klass in merged_classes
        },
        "slo": slo_verdict(recorder, factor=slo_factor),
        "knobs": knobs or {},
        "counters": counters or {},
        "lost": lost or [],
    }
    if corruption is not None:
        report["corruption"] = corruption
    report["ok"] = not report["lost"] and (
        corruption is None or bool(corruption.get("all_healed"))
    )
    return report


#: keys every SLO_r*.json must carry — weedload's smoke gate and the
#: harness tests both assert against this one list
REPORT_SCHEMA_KEYS = (
    "when", "kind", "workload", "chaos", "phases", "overall", "slo",
    "knobs", "counters", "lost", "ok",
)


# -- per-stage tail attribution (weedtrace aggregation) -----------------------

#: keys every TRACE_ATTRIB_r*.json must carry
TRACE_ATTRIB_SCHEMA_KEYS = (
    "when", "kind", "trace_count", "classes", "slowest",
)


def assemble_trace_attribution(
    traces: list,
    classes: tuple = ("healthy", "ec_intact", "degraded", "put"),
    kinds: tuple = ("http.read", "http.write"),
    slowest_n: int = 5,
) -> dict:
    """Fold scraped `/debug/traces` span trees into per-stage tail
    attribution: for each traffic class, the p50/p99 of the seconds each
    STAGE (span name) contributed to its requests' end-to-end latency.

    Stage seconds come from `obs.trace.attribute_stages`, which assigns
    every span its self-time and scales parallel children down to the
    wall time that actually passed — so per trace the stage seconds sum
    EXACTLY to the end-to-end duration, and per class
    `sum(stages[*].total_s) == e2e_total_s` (reported as
    `stage_coverage`, 1.0 by construction; the consistency gate the
    artifact is committed under). The `slowest` section carries the
    `slowest_n` slowest full traces (span trees included) across the
    selected classes — the exemplars behind the quantiles."""
    from seaweedfs_tpu.obs import trace as trace_mod

    picked = [
        t for t in traces
        if t.get("kind") in kinds and t.get("class") in classes
    ]
    e2e: dict[str, _Cell] = {}
    stage_cells: dict[str, dict[str, _Cell]] = {}
    stage_totals: dict[str, dict[str, float]] = {}
    e2e_totals: dict[str, float] = {}
    for t in picked:
        klass = t["class"]
        e2e.setdefault(klass, _Cell()).observe(t["duration_s"])
        e2e_totals[klass] = e2e_totals.get(klass, 0.0) + t["duration_s"]
        for stage, secs in trace_mod.attribute_stages(t).items():
            stage_cells.setdefault(klass, {}).setdefault(
                stage, _Cell()
            ).observe(secs)
            tot = stage_totals.setdefault(klass, {})
            tot[stage] = tot.get(stage, 0.0) + secs
    out_classes: dict[str, dict] = {}
    for klass, cell in sorted(e2e.items()):
        e2e_total = e2e_totals.get(klass, 0.0)
        stages = {}
        for stage, scell in sorted((stage_cells.get(klass) or {}).items()):
            s = scell.summary()
            total = stage_totals[klass][stage]
            stages[stage] = {
                "count": s["count"],
                "p50": s["p50"],
                "p99": s["p99"],
                "mean": s["mean"],
                "total_s": round(total, 6),
                # which stage OWNS the class's latency, in one number
                "share": round(total / e2e_total, 4) if e2e_total else 0.0,
            }
        stage_sum = sum(v["total_s"] for v in stages.values())
        out_classes[klass] = {
            "count": cell.summary()["count"],
            "e2e": cell.summary(),
            "stages": stages,
            "e2e_total_s": round(e2e_total, 6),
            "stage_total_s": round(stage_sum, 6),
            "stage_coverage": (
                round(stage_sum / e2e_total, 4) if e2e_total else 1.0
            ),
        }
    slowest = sorted(picked, key=lambda t: t["duration_s"], reverse=True)
    return {
        "when": time.strftime("%FT%TZ", time.gmtime()),
        "kind": "trace_attrib",
        "trace_count": len(picked),
        "classes": out_classes,
        "slowest": slowest[: max(0, int(slowest_n))],
    }


def write_trace_attribution(path: str, attrib: dict) -> None:
    for key in TRACE_ATTRIB_SCHEMA_KEYS:
        if key not in attrib:
            raise ValueError(f"trace attribution missing required key {key!r}")
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(attrib, f, indent=1)


def write_report(path: str, report: dict) -> None:
    for key in REPORT_SCHEMA_KEYS:
        if key not in report:
            raise ValueError(f"SLO report missing required key {key!r}")
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(report, f, indent=1)
