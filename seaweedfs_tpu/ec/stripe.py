"""Stripe engine — file-level EC encode/decode/rebuild with the exact layout
semantics of weed/storage/erasure_coding/ec_encoder.go + ec_decoder.go
[VERIFY: mount empty; upstream semantics per SURVEY.md §2.3].

Layout: a volume .dat is processed as block rows. While more than one full
large row (DATA_SHARDS x large_block) remains, encode large rows; the tail is
encoded as small rows, the last one zero-padded past EOF. Shard k's .ec{k:02d}
file is the concatenation of its column across rows. All 14 shard files end up
the same length.

TPU-first deviation from the reference's inner loop: the reference encodes
256 KiB buffer segments one at a time per goroutine; here segments are laid
out flat in a reused (shards, width) host staging buffer and dispatched as
ONE wide device matmul per batch (SURVEY.md §2.5 pipeline analog) — GF
matmul is column-independent, so the flat form is byte-identical to any
per-segment batching. The streaming paths run a configurable depth-N
inflight pipeline (double/triple buffering) over a ring of staging buffers:
batch K's parity/decode computes on-device while batches K+1..K+depth read
from disk, with no per-batch host allocation (readinto straight into the
staging ring, buffer donation releasing batch HBM early on device
backends) and the
per-shard CRC32 folded into the same pass so shard bytes are touched once.
"""

from __future__ import annotations

import json
import os
import zlib
from collections import deque
from contextlib import ExitStack
from typing import Optional, Sequence

import numpy as np

from seaweedfs_tpu.ec.constants import (
    DATA_SHARDS_COUNT,
    EC_BUFFER_SIZE,
    ERASURE_CODING_LARGE_BLOCK_SIZE,
    ERASURE_CODING_SMALL_BLOCK_SIZE,
    TOTAL_SHARDS_COUNT,
)
from seaweedfs_tpu.ops.rs_codec import Encoder, new_encoder
from seaweedfs_tpu.storage import idx as idx_mod
from seaweedfs_tpu.storage import types
from seaweedfs_tpu.storage.needle_map import MemDb


#: inflight depth of the streaming encode/rebuild pipelines: how many
#: batches may be in the read->device->write pipe at once. 1 restores the
#: pre-r6 behavior (one batch overlapped), 2 = double buffering, 3 = triple.
#: Deeper pipelines hide longer device/tunnel latencies at the cost of
#: (depth+1) staging buffers of `max_batch_bytes` each.
DEFAULT_PIPELINE_DEPTH = max(1, int(os.environ.get("WEEDTPU_PIPELINE_DEPTH", "2")))


def to_ext(shard_id: int) -> str:
    return f".ec{shard_id:02d}"


def shard_file_name(base_file_name: str, shard_id: int) -> str:
    return base_file_name + to_ext(shard_id)


def read_padded(f, offset: int, length: int) -> np.ndarray:
    """Read `length` bytes at `offset`, zero-padding past EOF."""
    f.seek(offset)
    raw = f.read(length)
    buf = np.zeros(length, dtype=np.uint8)
    if raw:
        buf[: len(raw)] = np.frombuffer(raw, dtype=np.uint8)
    return buf


def read_padded_into(f, offset: int, out: np.ndarray) -> None:
    """Read `out.size` bytes at `offset` straight into a contiguous uint8
    staging view, zero-filling past EOF — the zero-copy replacement for
    `read_padded` on the streaming paths (no bytes object, no frombuffer,
    no intermediate host copy per batch)."""
    f.seek(offset)
    got = f.readinto(memoryview(out)) or 0
    if got < out.size:
        out[got:] = 0


class _StagingRing:
    """`slots` reused host staging buffers for a depth-N pipeline.

    A slot is pinned from fill until its batch drains; with slots =
    pipeline_depth + 1 the round-robin take() never hands back a buffer
    whose batch is still inflight (the pipeline drains to < depth before
    every take)."""

    def __init__(self, slots: int, shape: tuple):
        self._bufs = [np.empty(shape, dtype=np.uint8) for _ in range(slots)]
        self._next = 0

    def take(self) -> np.ndarray:
        buf = self._bufs[self._next]
        self._next = (self._next + 1) % len(self._bufs)
        return buf


def _discard_inflight(inflight: deque) -> None:
    """Failure path: force every pending async dispatch to completion and
    drop the results, so teardown never races device work still reading
    from staging buffers. Errors here are suppressed — the original
    failure propagates from the caller."""
    while inflight:
        handle = inflight.popleft()[0]
        try:
            np.asarray(handle)
        except Exception:  # noqa: BLE001 — discarding, not reporting
            pass


def _encode_rows(
    f,
    enc: Encoder,
    outputs: Sequence,
    start_offset: int,
    block_size: int,
    n_rows: int,
    buffer_size: int,
    max_batch_bytes: int,
    pipeline_depth: Optional[int] = None,
    crcs: Optional[list] = None,
) -> None:
    """Encode `n_rows` rows of `block_size` blocks as a stream of flat
    (DATA_SHARDS, width) device dispatches over reused staging buffers.
    Output files receive bytes in row-major order.

    Depth-N pipeline: up to `pipeline_depth` batches' parity computes
    on-device (async dispatch) while the next batch's disk reads run;
    the np.asarray in drain_one() is the per-batch synchronization point,
    and drains happen FIFO so parity files receive bytes in order. Data
    shards stream to disk at fill time (their bytes never cross the
    device); when `crcs` is given, each shard's running CRC32 is folded
    in the same pass — bytes are touched once, no second host pass."""
    if n_rows <= 0:
        return
    if buffer_size > block_size:
        buffer_size = block_size
    if block_size % buffer_size:
        raise ValueError(f"block size {block_size} not a multiple of buffer {buffer_size}")
    depth = DEFAULT_PIPELINE_DEPTH if pipeline_depth is None else max(1, int(pipeline_depth))
    segs_per_row = block_size // buffer_size
    # how many (10 x buffer) segments fit the device-batch budget
    batch_cap = max(1, max_batch_bytes // (DATA_SHARDS_COUNT * buffer_size))
    span = batch_cap * buffer_size
    ring = _StagingRing(depth + 1, (DATA_SHARDS_COUNT, span))
    inflight: deque = deque()  # FIFO of (parity_handle, width)

    def drain_one() -> None:
        parity, width = inflight.popleft()
        parity_np = np.asarray(parity)  # sync point
        if DATA_SHARDS_COUNT + parity_np.shape[0] != len(outputs):
            # a geometry-mismatched encoder must fail loudly, not leave
            # trailing .ecNN files silently empty
            raise ValueError(
                f"encoder produced {parity_np.shape[0]} parity shards; "
                f"layout wants {len(outputs) - DATA_SHARDS_COUNT}"
            )
        for p in range(parity_np.shape[0]):
            row = np.ascontiguousarray(parity_np[p, :width])
            outputs[DATA_SHARDS_COUNT + p].write(row)
            if crcs is not None:
                crcs[DATA_SHARDS_COUNT + p] = zlib.crc32(row, crcs[DATA_SHARDS_COUNT + p])

    def flush(batch: list) -> None:
        if not batch:
            return
        width = len(batch) * buffer_size
        while len(inflight) >= depth:
            drain_one()
        staging = ring.take()
        # read runs of consecutive segments as one contiguous slab per shard
        # (10 large sequential reads per row-run instead of one seek per
        # segment x shard — keeps readahead alive at 1 GiB block strides)
        i = 0
        while i < len(batch):
            row, seg0 = batch[i]
            j = i
            while j + 1 < len(batch) and batch[j + 1] == (row, batch[j][1] + 1):
                j += 1
            row_start = start_offset + row * block_size * DATA_SHARDS_COUNT
            for d in range(DATA_SHARDS_COUNT):
                read_padded_into(
                    f,
                    row_start + d * block_size + seg0 * buffer_size,
                    staging[d, i * buffer_size : (j + 1) * buffer_size],
                )
            i = j + 1
        view = staging[:, :width]
        for d in range(DATA_SHARDS_COUNT):
            outputs[d].write(view[d])
            if crcs is not None:
                crcs[d] = zlib.crc32(view[d], crcs[d])
        inflight.append((enc.encode_parity_lazy(view, donate=True), width))

    try:
        # iterate segments in global order (row-major, then segment in block)
        pending: list = []  # (row, seg)
        for row in range(n_rows):
            for seg in range(segs_per_row):
                pending.append((row, seg))
                if len(pending) >= batch_cap:
                    flush(pending)
                    pending = []
        flush(pending)
        while inflight:
            drain_one()
    except BaseException:
        _discard_inflight(inflight)
        raise


def write_ec_files(
    base_file_name: str,
    large_block_size: int = ERASURE_CODING_LARGE_BLOCK_SIZE,
    small_block_size: int = ERASURE_CODING_SMALL_BLOCK_SIZE,
    buffer_size: int = EC_BUFFER_SIZE,
    encoder: Optional[Encoder] = None,
    max_batch_bytes: int = 64 * 1024 * 1024,
    pipeline_depth: Optional[int] = None,
) -> None:
    """<base>.dat -> <base>.ec00 .. .ec13 (WriteEcFiles semantics).

    Each shard's CRC32 is computed inline as its bytes stream through the
    encode pipeline (one touch per byte — no second host read-back pass)
    and recorded in the .eci sidecar for later shard verification. A
    mid-stream failure drains the inflight device work and unlinks every
    partial .ecNN file — a crashed encode never leaves a truncated shard
    set that a later rebuild would mistake for truth."""
    enc = encoder or new_encoder()
    dat_path = base_file_name + ".dat"
    dat_size = os.path.getsize(dat_path)
    large_row = large_block_size * DATA_SHARDS_COUNT
    small_row = small_block_size * DATA_SHARDS_COUNT

    n_large = 0
    remaining = dat_size
    while remaining > large_row:
        n_large += 1
        remaining -= large_row
    n_small = 0
    while remaining > 0:
        n_small += 1
        remaining -= small_row

    crcs = [0] * TOTAL_SHARDS_COUNT
    try:
        with ExitStack() as stack:
            f = stack.enter_context(open(dat_path, "rb"))
            outputs = [
                stack.enter_context(open(shard_file_name(base_file_name, s), "wb"))
                for s in range(TOTAL_SHARDS_COUNT)
            ]
            _encode_rows(
                f, enc, outputs, 0, large_block_size, n_large, buffer_size,
                max_batch_bytes, pipeline_depth, crcs,
            )
            _encode_rows(
                f,
                enc,
                outputs,
                n_large * large_row,
                small_block_size,
                n_small,
                min(buffer_size, small_block_size),
                max_batch_bytes,
                pipeline_depth,
                crcs,
            )
    except BaseException:
        for s in range(TOTAL_SHARDS_COUNT):
            try:
                os.unlink(shard_file_name(base_file_name, s))
            except OSError:
                pass
        raise
    write_ec_info(
        base_file_name, large_block_size, small_block_size, dat_size, shard_crcs=crcs
    )


def write_ec_info(
    base_file_name: str,
    large_block_size: int,
    small_block_size: int,
    dat_size: int,
    shard_crcs: Optional[Sequence[int]] = None,
) -> None:
    """Record the stripe geometry + true .dat size in an .eci sidecar.

    The reference needs no such file because its block sizes are compile-time
    constants; here they are parameters (tests use scaled-down geometry), and
    opening a shard set with the wrong geometry would silently mis-map
    intervals. Shard sets written by stock tooling (no .eci) still open fine
    with the default constants. `shard_crcs` (one CRC32 per shard file,
    computed inline by the streaming encode) rides along when available so
    rebuilds and fsck can verify shard integrity without a golden copy."""
    info = {
        "large_block_size": large_block_size,
        "small_block_size": small_block_size,
        "dat_size": dat_size,
    }
    if shard_crcs is not None:
        info["shard_crc32"] = [int(c) for c in shard_crcs]
    tmp = base_file_name + ".eci.tmp"
    with open(tmp, "w") as f:
        json.dump(info, f)
    os.replace(tmp, base_file_name + ".eci")


_ECI_KEYS = ("large_block_size", "small_block_size", "dat_size")


def read_ec_info(base_file_name: str) -> Optional[dict]:
    try:
        with open(base_file_name + ".eci") as f:
            info = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(info, dict) or not all(
        isinstance(info.get(k), int) for k in _ECI_KEYS
    ):
        return None
    return info


def write_sorted_file_from_idx(base_file_name: str, ext: str = ".ecx") -> None:
    """<base>.idx -> <base>.ecx: replay the index log, write entries sorted
    by needle id (WriteSortedFileFromIdx semantics)."""
    db = MemDb()
    db.load_from_idx(base_file_name + ".idx")
    db.save_to_idx(base_file_name + ext)


def generate_ec_files(
    base_file_name: str,
    **kwargs,
) -> None:
    """The VolumeEcShardsGenerate work: shards + sorted index."""
    write_ec_files(base_file_name, **kwargs)
    write_sorted_file_from_idx(base_file_name)


def find_local_shards(base_file_name: str) -> list[int]:
    return [
        s for s in range(TOTAL_SHARDS_COUNT) if os.path.exists(shard_file_name(base_file_name, s))
    ]


def _check_rebuild_geometry(base_file_name: str) -> tuple[list[int], list[int], int]:
    """Shared preflight for both rebuild paths: -> (present, missing,
    shard_size). Raises when fewer than DATA_SHARDS survive or survivors
    disagree on length (truncated shard)."""
    present = find_local_shards(base_file_name)
    missing = [s for s in range(TOTAL_SHARDS_COUNT) if s not in present]
    if not missing:
        return present, missing, 0
    if len(present) < DATA_SHARDS_COUNT:
        raise ValueError(
            f"cannot rebuild: only {len(present)} shards present, need {DATA_SHARDS_COUNT}"
        )
    sizes = {s: os.path.getsize(shard_file_name(base_file_name, s)) for s in present}
    if len(set(sizes.values())) != 1:
        raise IOError(f"surviving shards disagree on length: {sizes} — truncated shard?")
    return present, missing, sizes[present[0]]


def rebuild_ec_files(
    base_file_name: str,
    encoder: Optional[Encoder] = None,
    buffer_size: int = 4 * 1024 * 1024,
    max_batch_bytes: int = 64 * 1024 * 1024,
    pipeline_depth: Optional[int] = None,
) -> list[int]:
    """Reconstruct missing .ecNN files from >=10 survivors (RebuildEcFiles).

    The device-first repair path: each batch is one flat
    (survivors, width) slab — one contiguous read per survivor straight
    into a reused staging ring (no chunk transpose, no per-batch host
    allocation) decoded by ONE fused survivors->missing matrix in ONE
    device dispatch, with the same depth-N inflight pipeline as
    `_encode_rows`: up to `pipeline_depth` batches decode on-device while
    the next batch's slab reads run; drains are FIFO so rebuilt files
    receive bytes in order. Output is byte-identical to
    `rebuild_ec_files_serial` (zero-padding the tail slab is exact: GF
    matmul maps zero columns to zero columns, and the pad is trimmed
    before writing). Rebuilt shards' CRC32s are folded in as the bytes
    stream out and checked against the .eci-recorded values when present;
    a mid-stream failure (or CRC mismatch) drains inflight device work
    and unlinks the partial rebuilt files instead of leaking them.

    Returns the rebuilt shard ids."""
    enc = encoder or new_encoder()
    present, missing, shard_size = _check_rebuild_geometry(base_file_name)
    if not missing:
        return []
    depth = DEFAULT_PIPELINE_DEPTH if pipeline_depth is None else max(1, int(pipeline_depth))
    # first DATA_SHARDS present ids, exactly like Encoder._pick_survivors —
    # the serial path and this one must derive the SAME decode matrix
    survivors = present[:DATA_SHARDS_COUNT]
    chunks_per_batch = max(1, max_batch_bytes // (DATA_SHARDS_COUNT * buffer_size))
    span = chunks_per_batch * buffer_size
    ring = _StagingRing(depth + 1, (DATA_SHARDS_COUNT, span))
    crcs = {s: 0 for s in missing}
    try:
        with ExitStack() as stack:
            ins = {
                s: stack.enter_context(open(shard_file_name(base_file_name, s), "rb"))
                for s in survivors
            }
            outs = {
                s: stack.enter_context(open(shard_file_name(base_file_name, s), "wb"))
                for s in missing
            }
            inflight: deque = deque()  # FIFO of (decoded_handle, valid_bytes)

            def drain_one() -> None:
                lazy, valid = inflight.popleft()
                out = np.asarray(lazy)  # (len(missing), width) — sync point
                for k, s in enumerate(missing):
                    # contiguous row slice writes via the buffer protocol;
                    # the tail batch trims its zero-pad back off
                    row = out[k, :valid]
                    outs[s].write(row)
                    crcs[s] = zlib.crc32(row, crcs[s])

            try:
                for off in range(0, shard_size, span):
                    valid = min(span, shard_size - off)
                    width = -(-valid // buffer_size) * buffer_size
                    while len(inflight) >= depth:
                        drain_one()
                    staging = ring.take()
                    for i, s in enumerate(survivors):
                        read_padded_into(ins[s], off, staging[i, :width])
                    decoded = enc.reconstruct_lazy(
                        staging[:, :width], survivors, missing, donate=True
                    )  # async
                    inflight.append((decoded, valid))
                while inflight:
                    drain_one()
            except BaseException:
                _discard_inflight(inflight)
                raise
        _verify_rebuilt_crcs(base_file_name, crcs)
    except BaseException:
        for s in missing:
            try:
                os.unlink(shard_file_name(base_file_name, s))
            except OSError:
                pass
        raise
    return missing


def _verify_rebuilt_crcs(base_file_name: str, crcs: dict) -> None:
    """Integrity gate on the rebuild output: when the volume's .eci recorded
    per-shard CRC32s at encode time, a rebuilt shard whose streaming CRC
    disagrees means a silently-corrupt survivor (or a decode bug) produced
    garbage — fail the rebuild rather than ship a wrong shard."""
    info = read_ec_info(base_file_name)
    recorded = (info or {}).get("shard_crc32")
    if not isinstance(recorded, list) or len(recorded) != TOTAL_SHARDS_COUNT:
        return
    bad = {s: (c, recorded[s]) for s, c in crcs.items() if c != recorded[s]}
    if bad:
        raise IOError(
            f"rebuilt shard CRC mismatch vs .eci record: "
            f"{{shard: (got, want)}} = {bad} — corrupt survivor?"
        )


def rebuild_ec_files_serial(
    base_file_name: str,
    encoder: Optional[Encoder] = None,
    buffer_size: int = 4 * 1024 * 1024,
) -> list[int]:
    """The pre-pipeline serial rebuild: one blocking reconstruct per chunk.
    Kept as the correctness oracle (bench golden path + byte-identity
    tests) and the shape the AVX2-baseline comparison is defined against."""
    enc = encoder or new_encoder()
    present, missing, shard_size = _check_rebuild_geometry(base_file_name)
    if not missing:
        return []
    with ExitStack() as stack:
        ins = {
            s: stack.enter_context(open(shard_file_name(base_file_name, s), "rb"))
            for s in present
        }
        outs = {
            s: stack.enter_context(open(shard_file_name(base_file_name, s), "wb"))
            for s in missing
        }
        for off in range(0, shard_size, buffer_size):
            n = min(buffer_size, shard_size - off)
            shards: list[Optional[np.ndarray]] = [None] * TOTAL_SHARDS_COUNT
            for s in present:
                shards[s] = read_padded(ins[s], off, n)
            rec = enc.reconstruct(shards, wanted=missing)
            for s in missing:
                outs[s].write(np.ascontiguousarray(rec[s]))  # buffer-protocol write
    return missing


def write_dat_file(
    base_file_name: str,
    dat_file_size: Optional[int] = None,
    large_block_size: int = ERASURE_CODING_LARGE_BLOCK_SIZE,
    small_block_size: int = ERASURE_CODING_SMALL_BLOCK_SIZE,
) -> None:
    """Data shards -> <base>.dat (WriteDatFile / ec.decode semantics).

    Recorded .eci geometry overrides the arguments — decoding with the wrong
    block sizes would interleave garbage silently."""
    info = read_ec_info(base_file_name)
    if info is not None:
        large_block_size = info["large_block_size"]
        small_block_size = info["small_block_size"]
        if dat_file_size is None:
            dat_file_size = info["dat_size"]
    if dat_file_size is None:
        raise ValueError("dat_file_size required when no .eci sidecar exists")
    large_row = large_block_size * DATA_SHARDS_COUNT
    n_large = 0
    remaining = dat_file_size
    while remaining > large_row:
        n_large += 1
        remaining -= large_row

    with ExitStack() as stack:
        ins = [
            stack.enter_context(open(shard_file_name(base_file_name, s), "rb"))
            for s in range(DATA_SHARDS_COUNT)
        ]
        out = stack.enter_context(open(base_file_name + ".dat", "wb"))
        written = 0
        # large rows
        for row in range(n_large):
            for d in range(DATA_SHARDS_COUNT):
                ins[d].seek(row * large_block_size)
                out.write(ins[d].read(large_block_size))
                written += large_block_size
        # small rows
        small_start = n_large * large_block_size
        row = 0
        while written < dat_file_size:
            row_progress = 0
            for d in range(DATA_SHARDS_COUNT):
                if written >= dat_file_size:
                    break
                ins[d].seek(small_start + row * small_block_size)
                chunk = ins[d].read(small_block_size)
                take = min(len(chunk), dat_file_size - written)
                out.write(chunk[:take])
                written += take
                row_progress += take
            if row_progress == 0:
                raise IOError(
                    f"shards exhausted at {written} bytes but dat_file_size says "
                    f"{dat_file_size} — truncated shards or stale size"
                )
            row += 1


def write_idx_file_from_ec_index(base_file_name: str) -> None:
    """<base>.ecx + <base>.ecj -> <base>.idx (WriteIdxFileFromEcIndex):
    copy sorted entries, then append a tombstone per journaled deletion.
    Entries already tombstoned in .ecx (by compact_ecj) are normalized to
    the same (key, 0, -1) shape a journal replay would have appended."""
    with open(base_file_name + ".ecx", "rb") as f:
        ecx = f.read()
    entries = list(idx_mod.walk_index_buffer(ecx))
    deleted = read_ecj(base_file_name)
    with open(base_file_name + ".idx", "wb") as out:
        for key, off, size in entries:
            if types.is_deleted(size):
                out.write(types.pack_index_entry(key, 0, types.TOMBSTONE_FILE_SIZE))
            else:
                out.write(types.pack_index_entry(key, off, size))
        for key in deleted:
            out.write(types.pack_index_entry(key, 0, types.TOMBSTONE_FILE_SIZE))


# -- .ecj deletion journal ---------------------------------------------------


def append_ecj(base_file_name: str, needle_id: int) -> None:
    with open(base_file_name + ".ecj", "ab") as f:
        f.write(needle_id.to_bytes(types.NEEDLE_ID_SIZE, "big"))


def read_ecj(base_file_name: str) -> list[int]:
    path = base_file_name + ".ecj"
    if not os.path.exists(path):
        return []
    with open(path, "rb") as f:
        buf = f.read()
    n = len(buf) // types.NEEDLE_ID_SIZE
    return [
        int.from_bytes(buf[i * 8 : i * 8 + 8], "big") for i in range(n)
    ]


def compact_ecj(base_file_name: str) -> int:
    """Fold the deletion journal into the index (the reference compacts the
    .ecj on mount so a delete-heavy EC volume's journal doesn't grow without
    bound [ref: weed/storage/erasure_coding ecj replay/compact; SURVEY §5]):
    tombstone every journaled id in .ecx, then drop .ecj.

    Crash-safe ordering: write .ecx.cpt -> fsync -> rename over .ecx ->
    unlink .ecj. A crash before the rename leaves both files untouched; a
    crash after it leaves a stale .ecj whose replay only re-tombstones
    already-dead entries — idempotent either way. Returns the number of
    journal entries folded."""
    deleted = set(read_ecj(base_file_name))
    if not deleted:
        return 0
    ecx = base_file_name + ".ecx"
    with open(ecx, "rb") as f:
        buf = f.read()
    tmp = ecx + ".cpt"
    with open(tmp, "wb") as out:
        for key, off, size in idx_mod.walk_index_buffer(buf):
            if key in deleted and not types.is_deleted(size):
                size = types.TOMBSTONE_FILE_SIZE
            out.write(types.pack_index_entry(key, off, size))
        out.flush()
        os.fsync(out.fileno())
    os.replace(tmp, ecx)
    try:
        os.remove(base_file_name + ".ecj")
    except FileNotFoundError:
        pass
    return len(deleted)
