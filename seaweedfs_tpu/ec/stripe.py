"""Stripe engine — file-level EC encode/decode/rebuild with the exact layout
semantics of weed/storage/erasure_coding/ec_encoder.go + ec_decoder.go
[VERIFY: mount empty; upstream semantics per SURVEY.md §2.3].

Layout: a volume .dat is processed as block rows. While more than one full
large row (DATA_SHARDS x large_block) remains, encode large rows; the tail is
encoded as small rows, the last one zero-padded past EOF. Shard k's .ec{k:02d}
file is the concatenation of its column across rows. All 14 shard files end up
the same length.

TPU-first deviation from the reference's inner loop: the reference encodes
256 KiB buffer segments one at a time per goroutine; here segments are laid
out flat in a reused (shards, width) host staging buffer and dispatched as
ONE wide device matmul per batch (SURVEY.md §2.5 pipeline analog) — GF
matmul is column-independent, so the flat form is byte-identical to any
per-segment batching. The streaming paths run a configurable depth-N
inflight pipeline (double/triple buffering) over a ring of staging buffers:
batch K's parity/decode computes on-device while batches K+1..K+depth read
from disk, with no per-batch host allocation (readinto straight into the
staging ring, buffer donation releasing batch HBM early on device
backends) and the
per-shard CRC32 folded into the same pass so shard bytes are touched once.

The engine is backend-agnostic through the Encoder seam: the same flat
(shards, width) dispatch shape serves the device paths (jax/pallas/mesh)
and the CPU floor — including the compiled XOR-schedule backend
(ops/xorsched), whose width-axis cache tiling happens INSIDE the dispatch,
so the staging-batch geometry here needs no backend-specific casing.
"""

from __future__ import annotations

import json
import os
import threading
import zlib
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from contextlib import ExitStack
from typing import Callable, Optional, Sequence

import numpy as np

from seaweedfs_tpu.ec.constants import (
    DATA_SHARDS_COUNT,
    EC_BUFFER_SIZE,
    ERASURE_CODING_LARGE_BLOCK_SIZE,
    ERASURE_CODING_SMALL_BLOCK_SIZE,
    MAX_SHARD_COUNT,
)
from seaweedfs_tpu.ops.rs_codec import (
    CodeGeometry,
    DEFAULT_FAMILY,
    Encoder,
    family_of,
    geometry_for,
    new_encoder,
)
from seaweedfs_tpu.obs import trace as trace_mod
from seaweedfs_tpu.storage import idx as idx_mod
from seaweedfs_tpu.storage import types
from seaweedfs_tpu.storage.needle_map import MemDb
from seaweedfs_tpu.utils import config


#: inflight depth of the streaming encode/rebuild pipelines: how many
#: batches may be in the read->device->write pipe at once. 1 restores the
#: pre-r6 behavior (one batch overlapped), 2 = double buffering, 3 = triple.
#: Deeper pipelines hide longer device/tunnel latencies at the cost of
#: (depth+1) staging buffers of `max_batch_bytes` each.
DEFAULT_PIPELINE_DEPTH = config.env("WEEDTPU_PIPELINE_DEPTH")

#: how many batches AHEAD of the reading cursor the rebuild pipeline keeps
#: network-prefetched on remote slab sources (the third overlap stage: the
#: network fetches batch k+N while local readinto consumes batch k+1 and
#: the device decodes batch k). Defaults to the pipeline depth.
DEFAULT_PREFETCH_BATCHES = config.env("WEEDTPU_REBUILD_PREFETCH_BATCHES")

#: sub-range size for striped parallel range-fetches within one remote slab
#: window: a `max_batch_bytes`-sized window is split into stripes fetched
#: concurrently so one window's latency is holder-RTT + transfer/parallelism,
#: not a single serial stream.
DEFAULT_SLAB_STRIPE_BYTES = 4 * 1024 * 1024

#: concurrent sub-range fetches per remote source (slab or trace): the
#: striping fan-out that spreads one shard's windows across its replica
#: holders instead of pinning the first-sorted one.
DEFAULT_SLAB_FANOUT = config.env("WEEDTPU_SLAB_FANOUT")


def to_ext(shard_id: int) -> str:
    return f".ec{shard_id:02d}"


def shard_file_name(base_file_name: str, shard_id: int) -> str:
    return base_file_name + to_ext(shard_id)


def read_padded(f, offset: int, length: int) -> np.ndarray:
    """Read `length` bytes at `offset`, zero-padding past EOF."""
    f.seek(offset)
    raw = f.read(length)
    buf = np.zeros(length, dtype=np.uint8)
    if raw:
        buf[: len(raw)] = np.frombuffer(raw, dtype=np.uint8)
    return buf


def read_padded_into(f, offset: int, out: np.ndarray) -> None:
    """Read `out.size` bytes at `offset` straight into a contiguous uint8
    staging view, zero-filling past EOF — the zero-copy replacement for
    `read_padded` on the streaming paths (no bytes object, no frombuffer,
    no intermediate host copy per batch)."""
    f.seek(offset)
    got = f.readinto(memoryview(out)) or 0
    if got < out.size:
        out[got:] = 0


class _StagingRing:
    """`slots` reused host staging buffers for a depth-N pipeline.

    A slot is pinned from fill until its batch drains; with slots =
    pipeline_depth + 1 the round-robin take() never hands back a buffer
    whose batch is still inflight (the pipeline drains to < depth before
    every take)."""

    def __init__(self, slots: int, shape: tuple):
        self._bufs = [np.empty(shape, dtype=np.uint8) for _ in range(slots)]
        self._next = 0

    def take(self) -> np.ndarray:
        buf = self._bufs[self._next]
        self._next = (self._next + 1) % len(self._bufs)
        return buf


def _ring_for(cache: Optional[dict], slots: int, shape: tuple) -> _StagingRing:
    """A staging ring of the requested geometry, reused across calls when
    the caller supplies a cache dict (the inline-ingest poll path: one
    persistent ring per builder instead of fresh page-faulted buffers per
    poll). The cache is bounded — geometry churn (a seal's bigger batch
    after steady one-row polls) evicts the oldest entry."""
    if cache is None:
        return _StagingRing(slots, shape)
    key = (slots, shape)
    ring = cache.get(key)
    if ring is None:
        while len(cache) >= 2:
            cache.pop(next(iter(cache)))
        ring = cache[key] = _StagingRing(slots, shape)
    return ring


def _aligned(width: int, align: int) -> int:
    """Round a staged width up to the encoder's dispatch alignment (the
    mesh backend shards columns over dp*sp devices; single-device
    backends align to 1 and this is the identity)."""
    return -(-width // align) * align


def _abandon_future(fut) -> None:
    """Cancel an abandoned fetch future; if it is already running, attach a
    callback that observes (and drops) its outcome so late errors never
    surface as unretrieved-exception noise from a thread nobody waits on."""
    if not fut.cancel():
        fut.add_done_callback(_observe_and_drop)


def _observe_and_drop(fut) -> None:
    try:
        fut.result()
    except Exception:  # noqa: BLE001 — abandoned by design
        pass


def _discard_inflight(inflight: deque) -> None:
    """Failure path: force every pending async dispatch to completion and
    drop the results, so teardown never races device work still reading
    from staging buffers. Errors here are suppressed — the original
    failure propagates from the caller."""
    while inflight:
        handle = inflight.popleft()[0]
        try:
            np.asarray(handle)
        except Exception:  # noqa: BLE001 — discarding, not reporting
            pass


def _encode_rows(
    f,
    enc: Encoder,
    outputs: Sequence,
    start_offset: int,
    block_size: int,
    n_rows: int,
    buffer_size: int,
    max_batch_bytes: int,
    pipeline_depth: Optional[int] = None,
    crcs: Optional[list] = None,
    ring_cache: Optional[dict] = None,
) -> None:
    """Encode `n_rows` rows of `block_size` blocks as a stream of flat
    (DATA_SHARDS, width) device dispatches over reused staging buffers.
    Output files receive bytes in row-major order.

    Depth-N pipeline: up to `pipeline_depth` batches' parity computes
    on-device (async dispatch) while the next batch's disk reads run;
    the np.asarray in drain_one() is the per-batch synchronization point,
    and drains happen FIFO so parity files receive bytes in order. Data
    shards stream to disk at fill time (their bytes never cross the
    device); when `crcs` is given, each shard's running CRC32 is folded
    in the same pass — bytes are touched once, no second host pass.

    On a mesh-backend encoder the staging span is rounded up to the
    encoder's `width_align` (dp*sp) and each dispatch covers the aligned
    width (the gap zero-filled, written/CRC'd only to the true width), so
    every batch's host->device transfer splits evenly across the chips
    with no dispatcher-side pad copy. `ring_cache` (a caller-owned dict)
    keeps the staging ring alive ACROSS calls — the inline-ingest
    builder's per-poll path."""
    if n_rows <= 0:
        return
    if buffer_size > block_size:
        buffer_size = block_size
    if block_size % buffer_size:
        raise ValueError(f"block size {block_size} not a multiple of buffer {buffer_size}")
    depth = DEFAULT_PIPELINE_DEPTH if pipeline_depth is None else max(1, int(pipeline_depth))
    align = int(getattr(enc, "width_align", 1) or 1)
    k = enc.data_shards  # geometry-flexible: the encoder owns (k, m)
    segs_per_row = block_size // buffer_size
    # how many (k x buffer) segments fit the device-batch budget
    batch_cap = max(1, max_batch_bytes // (k * buffer_size))
    span = _aligned(batch_cap * buffer_size, align)
    ring = _ring_for(ring_cache, depth + 1, (k, span))
    inflight: deque = deque()  # FIFO of (parity_handle, width)

    def drain_one() -> None:
        parity, width = inflight.popleft()
        with trace_mod.span("encode.drain", width=width):
            parity_np = np.asarray(parity)  # sync point
        if k + parity_np.shape[0] != len(outputs):
            # a geometry-mismatched encoder must fail loudly, not leave
            # trailing .ecNN files silently empty
            raise ValueError(
                f"encoder produced {parity_np.shape[0]} parity shards; "
                f"layout wants {len(outputs) - k}"
            )
        for p in range(parity_np.shape[0]):
            row = np.ascontiguousarray(parity_np[p, :width])
            outputs[k + p].write(row)
            if crcs is not None:
                crcs[k + p] = zlib.crc32(row, crcs[k + p])

    def flush(batch: list) -> None:
        if not batch:
            return
        width = len(batch) * buffer_size
        while len(inflight) >= depth:
            drain_one()
        with trace_mod.span("encode.stage", width=width):
            staging = ring.take()
            # read runs of consecutive segments as one contiguous slab per
            # shard (k large sequential reads per row-run instead of one
            # seek per segment x shard — keeps readahead alive at 1 GiB
            # block strides)
            i = 0
            while i < len(batch):
                row, seg0 = batch[i]
                j = i
                while j + 1 < len(batch) and batch[j + 1] == (row, batch[j][1] + 1):
                    j += 1
                row_start = start_offset + row * block_size * k
                for d in range(k):
                    read_padded_into(
                        f,
                        row_start + d * block_size + seg0 * buffer_size,
                        staging[d, i * buffer_size : (j + 1) * buffer_size],
                    )
                i = j + 1
            view = staging[:, :width]
            for d in range(k):
                outputs[d].write(view[d])
                if crcs is not None:
                    crcs[d] = zlib.crc32(view[d], crcs[d])
            aw = _aligned(width, align)  # <= span: roundup is monotone
            if aw > width:
                staging[:, width:aw] = 0  # tail batch: pad columns are zeros
        inflight.append((enc.encode_parity_lazy(staging[:, :aw], donate=True), width))

    try:
        # iterate segments in global order (row-major, then segment in block)
        pending: list = []  # (row, seg)
        for row in range(n_rows):
            for seg in range(segs_per_row):
                pending.append((row, seg))
                if len(pending) >= batch_cap:
                    flush(pending)
                    pending = []
        flush(pending)
        while inflight:
            drain_one()
    except BaseException:
        _discard_inflight(inflight)
        raise


def stripe_layout(
    dat_size: int,
    large_block_size: int,
    small_block_size: int,
    data_shards: int = DATA_SHARDS_COUNT,
) -> tuple[int, int]:
    """(n_large, n_small) rows for a .dat of `dat_size` bytes — THE layout
    rule (WriteEcFiles semantics): while strictly more than one full large
    row remains, rows are large; the tail becomes small rows, the last one
    zero-padded past EOF. The ONE definition shared by the warm converter,
    the inline-ingest builder, and the geometry converter: their
    byte-identity contract is exactly this function agreeing with itself.
    `data_shards` is the row width in blocks (legacy default 10)."""
    large_row = large_block_size * data_shards
    small_row = small_block_size * data_shards
    n_large = 0
    remaining = dat_size
    while remaining > large_row:
        n_large += 1
        remaining -= large_row
    n_small = 0
    while remaining > 0:
        n_small += 1
        remaining -= small_row
    return n_large, n_small


def write_ec_files(
    base_file_name: str,
    large_block_size: int = ERASURE_CODING_LARGE_BLOCK_SIZE,
    small_block_size: int = ERASURE_CODING_SMALL_BLOCK_SIZE,
    buffer_size: int = EC_BUFFER_SIZE,
    encoder: Optional[Encoder] = None,
    max_batch_bytes: int = 64 * 1024 * 1024,
    pipeline_depth: Optional[int] = None,
) -> None:
    """<base>.dat -> <base>.ec00 .. .ec13 (WriteEcFiles semantics).

    Each shard's CRC32 is computed inline as its bytes stream through the
    encode pipeline (one touch per byte — no second host read-back pass)
    and recorded in the .eci sidecar for later shard verification. A
    mid-stream failure drains the inflight device work and unlinks every
    partial .ecNN file — a crashed encode never leaves a truncated shard
    set that a later rebuild would mistake for truth."""
    enc = encoder or new_encoder()
    dat_path = base_file_name + ".dat"
    dat_size = os.path.getsize(dat_path)
    large_row = large_block_size * enc.data_shards
    n_large, n_small = stripe_layout(
        dat_size, large_block_size, small_block_size, enc.data_shards
    )

    crcs = [0] * enc.total_shards
    try:
        with ExitStack() as stack:
            f = stack.enter_context(open(dat_path, "rb"))
            outputs = [
                stack.enter_context(open(shard_file_name(base_file_name, s), "wb"))
                for s in range(enc.total_shards)
            ]
            _encode_rows(
                f, enc, outputs, 0, large_block_size, n_large, buffer_size,
                max_batch_bytes, pipeline_depth, crcs,
            )
            _encode_rows(
                f,
                enc,
                outputs,
                n_large * large_row,
                small_block_size,
                n_small,
                min(buffer_size, small_block_size),
                max_batch_bytes,
                pipeline_depth,
                crcs,
            )
    except BaseException:
        for s in range(enc.total_shards):
            try:
                os.unlink(shard_file_name(base_file_name, s))
            except OSError:
                pass
        raise
    write_ec_info(
        base_file_name, large_block_size, small_block_size, dat_size,
        shard_crcs=crcs, geometry=geometry_of(enc),
    )


def geometry_of(enc: Encoder) -> CodeGeometry:
    """The encoder's geometry as a CodeGeometry record (family name from
    the registry when the triple matches one, else a `custom_K_M` tag)."""
    fam = enc.family or f"custom_{enc.data_shards}_{enc.parity_shards}"
    return CodeGeometry(
        fam, enc.data_shards, enc.parity_shards, enc.matrix_kind
    )


_LEGACY_GEOMETRY = geometry_for(DEFAULT_FAMILY)


def geometry_from_info(info: Optional[dict]) -> CodeGeometry:
    """The code geometry an .eci sidecar records — the LEGACY default
    (10+4 Vandermonde) when the sidecar is absent or predates geometry
    recording, so every pre-conversion shard set keeps reading exactly as
    before. Malformed geometry keys raise rather than silently misread."""
    if not info or "data_shards" not in info:
        return _LEGACY_GEOMETRY
    k = int(info["data_shards"])
    m = int(info["parity_shards"])
    kind = str(info.get("matrix_kind", "vandermonde"))
    if k <= 0 or m <= 0 or k + m > MAX_SHARD_COUNT:
        raise ValueError(
            f".eci records an unusable geometry: {k}+{m} (max total "
            f"{MAX_SHARD_COUNT})"
        )
    fam = str(info.get("family") or family_of(k, m, kind) or f"custom_{k}_{m}")
    return CodeGeometry(fam, k, m, kind)


def encoder_for_info(
    info: Optional[dict], default: Optional[Encoder] = None
) -> Encoder:
    """An encoder matching the .eci-recorded geometry. The supplied
    `default` (typically the server's shared encoder) is returned when its
    geometry already matches; otherwise a same-backend sibling is built so
    geometry-flexible volumes keep riding whatever kernel/mesh selection
    the factory measured fastest."""
    geom = geometry_from_info(info)
    if default is not None:
        if (
            default.data_shards == geom.data_shards
            and default.parity_shards == geom.parity_shards
            and default.matrix_kind == geom.matrix_kind
        ):
            return default
        enc = Encoder(
            geom.data_shards,
            geom.parity_shards,
            matrix_kind=geom.matrix_kind,
            backend=default.backend,
            pallas_mxu=default.pallas_mxu,
            pallas_tile=default.pallas_tile,
            mesh_shape=default.mesh_shape,
            mesh_rebuild=default.mesh_rebuild,
        )
        enc.selection = dict(
            default.selection, geometry=geom.family, source="geometry-sibling"
        )
        return enc
    return new_encoder(
        geom.data_shards, geom.parity_shards, matrix_kind=geom.matrix_kind
    )


def encoder_for_base(
    base_file_name: str, default: Optional[Encoder] = None
) -> Encoder:
    """`encoder_for_info` keyed by shard-set base path."""
    return encoder_for_info(read_ec_info(base_file_name), default)


def write_ec_info(
    base_file_name: str,
    large_block_size: int,
    small_block_size: int,
    dat_size: int,
    shard_crcs: Optional[Sequence[int]] = None,
    geometry: Optional[CodeGeometry] = None,
) -> None:
    """Record the stripe geometry + true .dat size in an .eci sidecar.

    The reference needs no such file because its block sizes are compile-time
    constants; here they are parameters (tests use scaled-down geometry), and
    opening a shard set with the wrong geometry would silently mis-map
    intervals. Shard sets written by stock tooling (no .eci) still open fine
    with the default constants. `shard_crcs` (one CRC32 per shard file,
    computed inline by the streaming encode) rides along when available so
    rebuilds and fsck can verify shard integrity without a golden copy.

    `geometry` records the code family/(k, m)/matrix kind for
    geometry-flexible volumes; the LEGACY default geometry is left implicit
    (absent keys read as 10+4 Vandermonde) so default-geometry sidecars stay
    byte-identical across every writer — warm, inline, rebuild, convert."""
    info = {
        "large_block_size": large_block_size,
        "small_block_size": small_block_size,
        "dat_size": dat_size,
    }
    if geometry is not None and (
        geometry.data_shards,
        geometry.parity_shards,
        geometry.matrix_kind,
    ) != (
        _LEGACY_GEOMETRY.data_shards,
        _LEGACY_GEOMETRY.parity_shards,
        _LEGACY_GEOMETRY.matrix_kind,
    ):
        info.update(
            data_shards=geometry.data_shards,
            parity_shards=geometry.parity_shards,
            matrix_kind=geometry.matrix_kind,
            family=geometry.family,
        )
    if shard_crcs is not None:
        info["shard_crc32"] = [int(c) for c in shard_crcs]
    tmp = base_file_name + ".eci.tmp"
    with open(tmp, "w") as f:
        json.dump(info, f)
        f.flush()
        os.fsync(f.fileno())  # the .eci is load-bearing: geometry + dat_size
    os.replace(tmp, base_file_name + ".eci")


_ECI_KEYS = ("large_block_size", "small_block_size", "dat_size")


def read_ec_info(base_file_name: str) -> Optional[dict]:
    try:
        with open(base_file_name + ".eci") as f:
            info = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(info, dict) or not all(
        isinstance(info.get(k), int) for k in _ECI_KEYS
    ):
        return None
    return info


def write_sorted_file_from_idx(base_file_name: str, ext: str = ".ecx") -> None:
    """<base>.idx -> <base>.ecx: replay the index log, write entries sorted
    by needle id (WriteSortedFileFromIdx semantics)."""
    db = MemDb()
    db.load_from_idx(base_file_name + ".idx")
    db.save_to_idx(base_file_name + ext)


def generate_ec_files(
    base_file_name: str,
    **kwargs,
) -> None:
    """The VolumeEcShardsGenerate work: shards + sorted index."""
    write_ec_files(base_file_name, **kwargs)
    write_sorted_file_from_idx(base_file_name)


def find_local_shards(base_file_name: str, total: Optional[int] = None) -> list[int]:
    """Shard ids with a local .ecNN file. The scan covers the registry-wide
    MAX_SHARD_COUNT bound by default so geometry-flexible shard sets (e.g.
    a converted 20+4 volume's .ec14-.ec23) are discovered; pass `total` to
    pin a known geometry."""
    return [
        s
        for s in range(total if total is not None else MAX_SHARD_COUNT)
        if os.path.exists(shard_file_name(base_file_name, s))
    ]


def _check_rebuild_geometry(
    base_file_name: str, enc: Encoder
) -> tuple[list[int], list[int], int]:
    """Shared preflight for both rebuild paths: -> (present, missing,
    shard_size). Raises when fewer than the geometry's data_shards survive
    or survivors disagree on length (truncated shard)."""
    present = find_local_shards(base_file_name, enc.total_shards)
    missing = [s for s in range(enc.total_shards) if s not in present]
    if not missing:
        return present, missing, 0
    if len(present) < enc.data_shards:
        raise ValueError(
            f"cannot rebuild: only {len(present)} shards present, need {enc.data_shards}"
        )
    sizes = {s: os.path.getsize(shard_file_name(base_file_name, s)) for s in present}
    if len(set(sizes.values())) != 1:
        raise IOError(f"surviving shards disagree on length: {sizes} — truncated shard?")
    return present, missing, sizes[present[0]]


# -- slab sources: where the rebuild pipeline's survivor bytes come from -----


class SlabSource:
    """One survivor shard's slab supplier for the rebuild pipeline.

    The pipeline calls `prefetch(offset, length)` for windows it will want
    soon (a hint — sources may start the work asynchronously) and
    `read_into(offset, out)` when the bytes must land in a staging view.
    Reads past the shard's end zero-fill, exactly like `read_padded_into`,
    so every backend is byte-interchangeable under the decode."""

    def prefetch(self, offset: int, length: int) -> None:  # noqa: B027 — hint
        pass

    def read_into(self, offset: int, out: np.ndarray) -> None:
        raise NotImplementedError

    def close(self) -> None:  # noqa: B027 — optional teardown
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class LocalSlabSource(SlabSource):
    """Today's path: `readinto` straight from a local shard file."""

    def __init__(self, path: str):
        # weedlint: ignore[open-no-ctx] handle owned by the source, closed in close()
        self._f = open(path, "rb")

    def read_into(self, offset: int, out: np.ndarray) -> None:
        read_padded_into(self._f, offset, out)

    def close(self) -> None:
        self._f.close()


class RemoteSlabSource(SlabSource):
    """Striped parallel range-fetches of one shard from its peer holders.

    `fetch(addr, offset, size) -> bytes` is the transport (injected by the
    cluster layer: the chunk-streamed, CRC-checked VolumeEcShardSlabRead
    RPC); it may return SHORT on EOF and must raise on any failure. A
    prefetched window is split into `stripe_bytes` sub-ranges submitted to
    the executor so the window's wall time is ~one holder round-trip, not a
    serial stream.

    Failover is per-holder and mid-rebuild: a failed fetch marks the
    holder dead and retries the range against the next holder (after a
    one-shot `refresh_holders()` re-lookup when all known holders are
    dead) WITHOUT disturbing other inflight ranges — the batch pipeline
    never restarts. Dead holders are recorded in `self.failovers` for
    observability. Raises IOError when no holder can serve a range.

    Multi-holder striping (the PR-3-named follow-up): up to `fanout`
    stripes run concurrently and each picks the live holder with the
    FEWEST inflight fetches (ties broken by per-stripe rotation), so a
    replicated shard's windows aggregate bandwidth across all its
    holders — and when one holder dies the load rebalances onto the
    rest instead of serializing behind a static modulo assignment."""

    def __init__(
        self,
        shard_id: int,
        holders: Sequence[str],
        fetch: Callable[[str, int, int], bytes],
        executor: Optional[ThreadPoolExecutor] = None,
        stripe_bytes: int = DEFAULT_SLAB_STRIPE_BYTES,
        refresh_holders: Optional[Callable[[], Sequence[str]]] = None,
        fetch_deadline: float = 120.0,
        fanout: Optional[int] = None,
    ):
        self.shard_id = shard_id
        self.failovers: list[str] = []
        #: payload bytes this source pulled over the network (the
        #: repair-bandwidth accounting input: moved-bytes, not
        #: repaired-bytes)
        self.bytes_fetched = 0
        self._holders = [str(h) for h in holders]
        self._dead: set[str] = set()
        self._fetch = fetch
        self._refresh = refresh_holders
        # bounded, not one-shot: a transient error may kill the only known
        # holder more than once over a GB-scale rebuild; each refresh
        # resurrects re-listed holders, while the bound still guarantees
        # termination against a genuinely dead cluster
        self._refreshes_left = 2
        self._stripe = max(64 * 1024, int(stripe_bytes))
        self._deadline = fetch_deadline
        self._lock = threading.Lock()
        # the rebuild's ambient span, captured at construction: fetches
        # run on pool threads, and the holder-bound RPCs must carry the
        # rebuild's trace id across the wire (ContextVars don't cross
        # executor submission)
        self._trace_parent = trace_mod.current()
        self._fanout = DEFAULT_SLAB_FANOUT if fanout is None else max(1, int(fanout))
        #: holder -> fetches currently running against it (striping load)
        self._inflight: dict[str, int] = {}
        self._own_executor = executor is None
        self._ex = executor or ThreadPoolExecutor(
            max_workers=self._fanout, thread_name_prefix=f"slab-fetch-{shard_id}"
        )
        #: offset -> (length, [(rel_offset, size, Future[bytes]), ...])
        self._pending: dict[int, tuple[int, list]] = {}

    def _live_holders(self) -> list[str]:
        with self._lock:
            live = [h for h in self._holders if h not in self._dead]
            if live or self._refresh is None or self._refreshes_left <= 0:
                return live
            self._refreshes_left -= 1
        try:
            fresh = list(self._refresh() or ())
        except Exception:  # noqa: BLE001 — a dead master is "no holders"
            fresh = []
        with self._lock:
            for h in fresh:
                if h not in self._holders:
                    self._holders.append(str(h))
                self._dead.discard(str(h))
            return [h for h in self._holders if h not in self._dead]

    def _pick_holder(self, live: list[str], offset: int) -> str:
        """Least-inflight live holder; per-stripe rotation breaks ties so
        an idle source still spreads consecutive windows across replicas
        instead of always re-picking the first-sorted holder."""
        with self._lock:
            rot = (offset // self._stripe) % len(live)
            order = live[rot:] + live[:rot]
            addr = min(order, key=lambda h: self._inflight.get(h, 0))
            self._inflight[addr] = self._inflight.get(addr, 0) + 1
            return addr

    def _fetch_range(self, offset: int, size: int) -> bytes:
        with trace_mod.attach(self._trace_parent):
            return self._fetch_range_inner(offset, size)

    def _fetch_range_inner(self, offset: int, size: int) -> bytes:
        while True:
            live = self._live_holders()
            if not live:
                raise IOError(
                    f"shard {self.shard_id}: no reachable holder for "
                    f"[{offset}, {offset + size}) — tried {self._holders}"
                )
            addr = self._pick_holder(live, offset)
            try:
                data = self._fetch(addr, offset, size)
            except Exception:  # noqa: BLE001 — holder down: fail over
                with self._lock:
                    self._inflight[addr] = max(0, self._inflight.get(addr, 1) - 1)
                    if addr not in self._dead:
                        self._dead.add(addr)
                        self.failovers.append(addr)
                continue
            with self._lock:
                self._inflight[addr] = max(0, self._inflight.get(addr, 1) - 1)
                self.bytes_fetched += len(data)
            if len(data) > size:
                raise IOError(
                    f"shard {self.shard_id}: holder {addr} over-answered "
                    f"({len(data)} > {size} bytes)"
                )
            return data

    def prefetch(self, offset: int, length: int) -> None:
        if length <= 0 or offset in self._pending:
            return
        futs = []
        for off in range(offset, offset + length, self._stripe):
            n = min(self._stripe, offset + length - off)
            futs.append((off - offset, n, self._ex.submit(self._fetch_range, off, n)))
        self._pending[offset] = (length, futs)

    def read_into(self, offset: int, out: np.ndarray) -> None:
        entry = self._pending.pop(offset, None)
        if entry is not None and entry[0] != out.size:
            for _, _, fut in entry[1]:  # stale window shape: refetch
                _abandon_future(fut)
            entry = None
        if entry is None:
            self.prefetch(offset, out.size)
            entry = self._pending.pop(offset)
        _, futs = entry
        # the wait must outlive failover: a holder that HANGS (no error
        # until the transport deadline) burns one full fetch_deadline
        # before the worker retries the next holder, so budget one
        # deadline per holder we could try, plus one for the refresh
        with self._lock:
            wait_budget = self._deadline * (len(self._holders) + 1)
        try:
            for rel, n, fut in futs:
                data = fut.result(timeout=wait_budget)
                got = len(data)
                if got:
                    out[rel : rel + got] = np.frombuffer(data, dtype=np.uint8)
                if got < n:  # EOF inside the window: zero-fill, like local
                    out[rel + got : rel + n] = 0
        except BaseException:
            for _, _, fut in futs:
                _abandon_future(fut)
            raise

    def close(self) -> None:
        for _, futs in self._pending.values():
            for _, _, fut in futs:
                _abandon_future(fut)
        self._pending.clear()
        if self._own_executor:
            self._ex.shutdown(wait=False, cancel_futures=True)


# -- trace-repair projection sources -----------------------------------------
#
# The repair-bandwidth lever (PAPERS.md: "Practical Considerations in
# Repairing Reed-Solomon Codes", regenerating-code helpers): a holder of
# several survivor shards ships the GF(2^8) PROJECTION of its local group
# through the decode matrix — `rows = len(missing)` projected rows per
# holder — instead of one full slab per survivor. XORing the holders'
# projections IS the fused decode (GF addition is XOR and matrix products
# split column-wise), so the rebuilt bytes are identical to the slab path
# while the wire moves holders x repaired-bytes, not survivors x shard-bytes.


class TraceSlabSource(SlabSource):
    """One holder group's repair-projection supplier.

    `fetch(offset, size) -> bytes` is the transport, already bound to the
    holder and its projection terms by the cluster layer (the projection
    mode of the CRC-framed VolumeEcShardSlabRead RPC); it returns the
    ROW-MAJOR (rows, actual) projected block for the window, where
    `actual = min(size, shard_len - offset)` — short on EOF exactly like
    a slab, and the client zero-fills (projections of zero columns are
    zero). Windows are split into `chunk_bytes` sub-ranges fetched in
    parallel (projection is per-byte-column, so sub-ranges concatenate
    exactly).

    NO in-source failover: the group's shards live on THIS holder, so a
    failed fetch propagates and the caller falls back to full-slab
    sources (capability negotiation and chaos both land there)."""

    def __init__(
        self,
        holder: str,
        shard_ids: Sequence[int],
        rows: int,
        fetch: Callable[[int, int], bytes],
        executor: Optional[ThreadPoolExecutor] = None,
        chunk_bytes: Optional[int] = None,
        fanout: Optional[int] = None,
    ):
        if rows <= 0:
            raise ValueError("projection rows must be positive")
        self.holder = str(holder)
        self.shard_ids = [int(s) for s in shard_ids]
        self.rows = int(rows)
        self.bytes_fetched = 0
        self._fetch = fetch
        self._chunk = max(
            64 * 1024,
            int(config.env("WEEDTPU_TRACE_CHUNK") if chunk_bytes is None else chunk_bytes),
        )
        self._lock = threading.Lock()
        # same bridge as RemoteSlabSource: projection fetches run on pool
        # threads but must ride the rebuild's trace id over the wire
        self._trace_parent = trace_mod.current()
        self._own_executor = executor is None
        workers = DEFAULT_SLAB_FANOUT if fanout is None else max(1, int(fanout))
        self._ex = executor or ThreadPoolExecutor(
            max_workers=workers, thread_name_prefix=f"trace-fetch-{self.holder}"
        )
        #: window offset -> (per-shard length, [(rel, size, Future), ...])
        self._pending: dict[int, tuple[int, list]] = {}

    def _fetch_counted(self, offset: int, size: int) -> bytes:
        with trace_mod.attach(self._trace_parent):
            return self._fetch_counted_inner(offset, size)

    def _fetch_counted_inner(self, offset: int, size: int) -> bytes:
        data = self._fetch(offset, size)
        if len(data) % self.rows:
            raise IOError(
                f"trace group {self.holder}: projected stream length "
                f"{len(data)} is not a multiple of {self.rows} rows"
            )
        if len(data) > size * self.rows:
            raise IOError(
                f"trace group {self.holder}: over-answered "
                f"({len(data)} > {size * self.rows} bytes)"
            )
        with self._lock:
            self.bytes_fetched += len(data)
        return data

    def prefetch(self, offset: int, length: int) -> None:
        if length <= 0 or offset in self._pending:
            return
        futs = []
        for off in range(offset, offset + length, self._chunk):
            n = min(self._chunk, offset + length - off)
            futs.append(
                (off - offset, n, self._ex.submit(self._fetch_counted, off, n))
            )
        self._pending[offset] = (length, futs)

    def read_into(self, offset: int, out: np.ndarray) -> None:
        """Fill a flat (rows * width,) staging view with the window's
        projected block: row-major (rows, width), EOF zero-filled."""
        if out.size % self.rows:
            raise ValueError(
                f"staging view of {out.size} bytes is not {self.rows} rows"
            )
        width = out.size // self.rows
        entry = self._pending.pop(offset, None)
        if entry is not None and entry[0] != width:
            for _, _, fut in entry[1]:  # stale window shape: refetch
                _abandon_future(fut)
            entry = None
        if entry is None:
            self.prefetch(offset, width)
            entry = self._pending.pop(offset)
        _, futs = entry
        out2d = out.reshape(self.rows, width)
        try:
            for rel, n, fut in futs:
                data = fut.result()
                sub = len(data) // self.rows
                if sub:
                    out2d[:, rel : rel + sub] = np.frombuffer(
                        data, dtype=np.uint8
                    ).reshape(self.rows, sub)
                if sub < n:  # EOF inside the window: zero-fill, like local
                    out2d[:, rel + sub : rel + n] = 0
        except BaseException:
            for _, _, fut in futs:
                _abandon_future(fut)
            raise

    def close(self) -> None:
        for _, futs in self._pending.values():
            for _, _, fut in futs:
                _abandon_future(fut)
        self._pending.clear()
        if self._own_executor:
            self._ex.shutdown(wait=False, cancel_futures=True)


class LocalProjectionSource(SlabSource):
    """The rebuild target's own survivors as one projection group: reads
    the local shard windows and projects them through the group's decode
    coefficients with the SAME math the remote holders run server-side —
    so local and remote groups are interchangeable rows of the trace
    combine, and local survivors cost zero wire bytes."""

    def __init__(self, paths: Sequence[str], coeffs: np.ndarray, encoder):
        coeffs = np.asarray(coeffs, dtype=np.uint8)
        if coeffs.ndim != 2 or coeffs.shape[1] != len(paths):
            raise ValueError(
                f"want (rows, {len(paths)}) coeffs, got {coeffs.shape}"
            )
        self.holder = "local"
        self.rows = coeffs.shape[0]
        self.bytes_fetched = 0  # never leaves the machine
        self._coeffs = coeffs
        self._enc = encoder
        # weedlint: ignore[open-no-ctx] handles owned by the source, closed in close()
        self._files = [open(p, "rb") for p in paths]

    def read_into(self, offset: int, out: np.ndarray) -> None:
        if out.size % self.rows:
            raise ValueError(
                f"staging view of {out.size} bytes is not {self.rows} rows"
            )
        width = out.size // self.rows
        stack = np.empty((len(self._files), width), dtype=np.uint8)
        for i, f in enumerate(self._files):
            read_padded_into(f, offset, stack[i])
        out.reshape(self.rows, width)[:] = self._enc.project(self._coeffs, stack)

    def close(self) -> None:
        for f in self._files:
            f.close()


def rebuild_ec_files_from_projections(
    base_file_name: str,
    groups: Sequence[SlabSource],
    shard_size: int,
    missing: Sequence[int],
    encoder: Optional[Encoder] = None,
    buffer_size: int = 4 * 1024 * 1024,
    max_batch_bytes: int = 64 * 1024 * 1024,
    pipeline_depth: Optional[int] = None,
    prefetch_batches: Optional[int] = None,
) -> list[int]:
    """The trace-combine rebuild pipeline: every batch reads one
    (rows x width) projected block per holder group and reconstructs the
    missing shards with ONE fused combine dispatch — the XOR of the
    groups' partial projections, expressed as an all-ones GF(2^8) matrix
    applied to the (groups, rows*width) staging stack, so it rides the
    same async-dispatch/donation/staging-ring machinery as the slab
    pipeline. Output is byte-identical to `rebuild_ec_files_serial` on
    the same survivor set (the projection coefficients ARE the fused
    decode matrix, split column-wise across holders); CRC32 is folded in
    as bytes stream out and checked against the .eci record; any failure
    drains inflight device work and unlinks the partial outputs."""
    enc = encoder or encoder_for_base(base_file_name)
    missing = sorted(int(s) for s in missing)
    if not missing:
        return []
    if not groups:
        raise ValueError("trace rebuild needs at least one projection group")
    rows = len(missing)
    for g in groups:
        if getattr(g, "rows", None) != rows:
            raise ValueError(
                f"group {getattr(g, 'holder', g)!r} projects "
                f"{getattr(g, 'rows', None)} rows, want {rows}"
            )
    depth = DEFAULT_PIPELINE_DEPTH if pipeline_depth is None else max(1, int(pipeline_depth))
    ahead = (
        DEFAULT_PREFETCH_BATCHES if prefetch_batches is None else max(1, int(prefetch_batches))
    )
    chunks_per_batch = max(1, max_batch_bytes // (enc.data_shards * buffer_size))
    span = chunks_per_batch * buffer_size
    combine = np.ones((1, len(groups)), dtype=np.uint8)  # GF sum == XOR
    ring = _StagingRing(depth + 1, (len(groups), rows * span))
    crcs = {s: 0 for s in missing}
    batches = []
    off = 0
    while off < shard_size:
        valid = min(span, shard_size - off)
        batches.append((off, valid, -(-valid // buffer_size) * buffer_size))
        off += span
    try:
        with ExitStack() as stack:
            outs = {
                s: stack.enter_context(open(shard_file_name(base_file_name, s), "wb"))
                for s in missing
            }
            inflight: deque = deque()  # FIFO of (combined_handle, valid, width)

            def drain_one() -> None:
                lazy, valid, width = inflight.popleft()
                with trace_mod.span("rebuild.drain", width=width):
                    out = np.asarray(lazy).reshape(rows, width)  # sync point
                    for k, s in enumerate(missing):
                        row = np.ascontiguousarray(out[k, :valid])
                        outs[s].write(row)
                        crcs[s] = zlib.crc32(row, crcs[s])

            def issue_prefetch(bi: int) -> None:
                if bi < len(batches):
                    o, _, wd = batches[bi]
                    for g in groups:
                        g.prefetch(o, wd)

            try:
                for j in range(min(ahead, len(batches))):
                    issue_prefetch(j)
                for bi, (off, valid, width) in enumerate(batches):
                    issue_prefetch(bi + ahead)  # network runs ahead of reads
                    while len(inflight) >= depth:
                        drain_one()
                    with trace_mod.span("rebuild.stage", batch=bi, width=width):
                        staging = ring.take()
                        for i, g in enumerate(groups):
                            g.read_into(off, staging[i, : rows * width])
                    combined = enc.project_lazy(
                        combine, staging[:, : rows * width], donate=True
                    )  # async
                    inflight.append((combined, valid, width))
                while inflight:
                    drain_one()
            except BaseException:
                _discard_inflight(inflight)
                raise
        _verify_rebuilt_crcs(base_file_name, crcs)
    except BaseException:
        for s in missing:
            try:
                os.unlink(shard_file_name(base_file_name, s))
            except OSError:
                pass
        raise
    return missing


def rebuild_ec_files_from_sources(
    base_file_name: str,
    sources: dict[int, SlabSource],
    shard_size: int,
    encoder: Optional[Encoder] = None,
    missing: Optional[Sequence[int]] = None,
    buffer_size: int = 4 * 1024 * 1024,
    max_batch_bytes: int = 64 * 1024 * 1024,
    pipeline_depth: Optional[int] = None,
    prefetch_batches: Optional[int] = None,
) -> list[int]:
    """The generalized (local OR remote survivor) rebuild pipeline.

    `sources` maps present shard id -> SlabSource; `missing` defaults to
    every shard id absent from it. Survivor selection is the first
    DATA_SHARDS of the sorted present ids — the same rule as
    `rebuild_ec_files_serial` on the same survivor set, so output bytes are
    identical regardless of where survivors live. Triple overlap: remote
    sources are told to prefetch batch k+`prefetch_batches` (network) while
    batch k+1 fills staging (disk / prefetched-buffer copy) and batch k
    decodes on-device through the same depth-N inflight deque as the local
    path. Rebuilt shards stream to `<base>.ecNN` with CRC32 folded in and
    verified against the .eci record when present; any failure drains
    inflight device work and unlinks the partial outputs."""
    enc = encoder or encoder_for_base(base_file_name)
    present = sorted(sources)
    if missing is None:
        missing = [s for s in range(enc.total_shards) if s not in sources]
    missing = sorted(missing)
    if not missing:
        return []
    if len(present) < enc.data_shards:
        raise ValueError(
            f"cannot rebuild: only {len(present)} shards present, need {enc.data_shards}"
        )
    depth = DEFAULT_PIPELINE_DEPTH if pipeline_depth is None else max(1, int(pipeline_depth))
    ahead = (
        DEFAULT_PREFETCH_BATCHES if prefetch_batches is None else max(1, int(prefetch_batches))
    )
    survivors = present[: enc.data_shards]
    align = int(getattr(enc, "width_align", 1) or 1)
    chunks_per_batch = max(1, max_batch_bytes // (enc.data_shards * buffer_size))
    span = _aligned(chunks_per_batch * buffer_size, align)
    ring = _StagingRing(depth + 1, (enc.data_shards, span))
    crcs = {s: 0 for s in missing}
    #: (offset, valid_bytes, staged_width) per batch, precomputed so the
    #: prefetch cursor can run `ahead` batches past the read cursor
    batches = []
    off = 0
    while off < shard_size:
        valid = min(span, shard_size - off)
        batches.append((off, valid, -(-valid // buffer_size) * buffer_size))
        off += span
    try:
        with ExitStack() as stack:
            outs = {
                s: stack.enter_context(open(shard_file_name(base_file_name, s), "wb"))
                for s in missing
            }
            inflight: deque = deque()  # FIFO of (decoded_handle, valid_bytes)

            def drain_one() -> None:
                lazy, valid = inflight.popleft()
                with trace_mod.span("rebuild.drain", width=valid):
                    out = np.asarray(lazy)  # (len(missing), width) — sync point
                    for k, s in enumerate(missing):
                        row = out[k, :valid]
                        outs[s].write(row)
                        crcs[s] = zlib.crc32(row, crcs[s])

            def issue_prefetch(bi: int) -> None:
                if bi < len(batches):
                    o, _, wd = batches[bi]
                    for s in survivors:
                        sources[s].prefetch(o, wd)

            try:
                for j in range(min(ahead, len(batches))):
                    issue_prefetch(j)
                for bi, (off, valid, width) in enumerate(batches):
                    issue_prefetch(bi + ahead)  # network runs ahead of reads
                    while len(inflight) >= depth:
                        drain_one()
                    with trace_mod.span("rebuild.stage", batch=bi, width=width):
                        staging = ring.take()
                        for i, s in enumerate(survivors):
                            sources[s].read_into(off, staging[i, :width])
                        aw = _aligned(width, align)  # <= span: roundup is monotone
                        if aw > width:
                            staging[:, width:aw] = 0  # tail: pad columns are zeros
                    decoded = enc.reconstruct_lazy(
                        staging[:, :aw], survivors, missing, donate=True
                    )  # async
                    inflight.append((decoded, valid))
                while inflight:
                    drain_one()
            except BaseException:
                _discard_inflight(inflight)
                raise
        _verify_rebuilt_crcs(base_file_name, crcs)
    except BaseException:
        for s in missing:
            try:
                os.unlink(shard_file_name(base_file_name, s))
            except OSError:
                pass
        raise
    return missing


def rebuild_ec_files_batch(
    jobs: list[dict],
    encoder: Optional[Encoder] = None,
    buffer_size: int = 4 * 1024 * 1024,
    max_batch_bytes: int = 64 * 1024 * 1024,
    pipeline_depth: Optional[int] = None,
    prefetch_batches: Optional[int] = None,
    fuse: Optional[bool] = None,
) -> dict:
    """MANY volumes' rebuilds through SHARED device dispatches — the
    fleet-repair batch engine (and the PR 9 residual: dp used to shard
    one volume's staging width, so a storm of small volumes paid a
    partial-width dispatch each).

    Each job is {"base", "sources" ({shard id -> SlabSource}),
    "shard_size", "missing" (optional)}. Jobs whose (survivor set,
    missing set, geometry) SIGNATURE matches share one fused decode
    matrix, and batches are WIDTH-PACKED across volume boundaries: a
    batch window fills with volume A's tail and volume B's head side by
    side (the GF matmul is column-independent, so which volume a column
    came from is purely a scatter concern at drain time). Small stripes
    therefore ride full-width dispatches instead of one shallow dispatch
    per volume.

    With `fuse` (default WEEDTPU_REBUILD_FUSE), DIFFERENT signatures
    fuse too: every group becomes one BLOCK of a block-diagonal decode
    (Encoder.reconstruct_block) and the whole heterogeneous cohort runs
    through ONE staging-ring pipeline — dispatch_groups == 1 for any mix
    of geometries and loss patterns. Groups keep insertion order, so the
    caller's job order IS the block order. fuse=False restores one
    pipeline per signature group (the bench baseline).

    Failure semantics are GROUP-scoped either way: a failure unlinks
    every partial output of that signature group's members and records
    the error per job; other groups still run/complete. Returns
      {"rebuilt": {base: [shard ids]}, "errors": {base: str},
       "dispatch_groups": int, "signature_groups": int,
       "volumes_fused": int, "block_order": [base, ...]}."""
    enc_default = encoder
    groups: dict[tuple, list[dict]] = {}
    out: dict = {
        "rebuilt": {},
        "errors": {},
        "dispatch_groups": 0,
        "signature_groups": 0,
        "volumes_fused": 0,
        "block_order": [],
    }
    for job in jobs:
        enc = job.get("encoder") or enc_default or encoder_for_base(job["base"])
        present = sorted(job["sources"])
        missing = job.get("missing")
        if missing is None:  # an explicit [] means "nothing to rebuild",
            # NOT "compute it" — a healed volume must come back rebuilt=[]
            missing = [s for s in range(enc.total_shards) if s not in job["sources"]]
        missing = sorted(missing)
        if not missing:
            out["rebuilt"][job["base"]] = []
            continue
        if len(present) < enc.data_shards:
            out["errors"][job["base"]] = (
                f"only {len(present)} shards present, need {enc.data_shards}"
            )
            continue
        survivors = tuple(present[: enc.data_shards])
        sig = (
            survivors,
            tuple(missing),
            enc.data_shards,
            enc.total_shards,
            getattr(enc, "matrix_kind", ""),
        )
        groups.setdefault(sig, []).append(
            {**job, "encoder": enc, "missing": missing, "survivors": survivors}
        )
    depth = DEFAULT_PIPELINE_DEPTH if pipeline_depth is None else max(1, int(pipeline_depth))
    ahead = (
        DEFAULT_PREFETCH_BATCHES if prefetch_batches is None else max(1, int(prefetch_batches))
    )
    out["signature_groups"] = len(groups)
    out["block_order"] = [job["base"] for members in groups.values() for job in members]
    out["volumes_fused"] = len(out["block_order"])
    if fuse is None:
        fuse = config.env("WEEDTPU_REBUILD_FUSE") == "on"
    if fuse and groups:
        out["dispatch_groups"] = 1
        glist = list(groups.values())
        try:
            rebuilt, errors = _rebuild_fused(
                glist, depth, ahead, buffer_size, max_batch_bytes
            )
            out["rebuilt"].update(rebuilt)
            out["errors"].update(errors)
        except BaseException as e:
            for members in glist:
                for job in members:
                    for s in job["missing"]:
                        try:
                            os.unlink(shard_file_name(job["base"], s))
                        except OSError:
                            pass
                    out["errors"][job["base"]] = f"{type(e).__name__}: {e}"[:300]
            if not isinstance(e, Exception):
                raise
        return out
    for sig, members in groups.items():
        out["dispatch_groups"] += 1
        try:
            _rebuild_group(members, depth, ahead, buffer_size, max_batch_bytes)
            for job in members:
                out["rebuilt"][job["base"]] = list(job["missing"])
        except BaseException as e:
            for job in members:
                for s in job["missing"]:
                    try:
                        os.unlink(shard_file_name(job["base"], s))
                    except OSError:
                        pass
                out["errors"][job["base"]] = f"{type(e).__name__}: {e}"[:300]
            if not isinstance(e, Exception):
                # KeyboardInterrupt/SystemExit: partials are cleaned, but
                # the interrupt must propagate, not be absorbed into a
                # per-volume error string while later groups keep running
                raise
    return out


def _rebuild_fused(
    groups: list[list[dict]], depth: int, ahead: int, buffer_size: int,
    max_batch_bytes: int,
) -> tuple[dict, dict]:
    """The heterogeneous cohort as ONE pipeline: every signature group is a
    block of a block-diagonal decode, and each staging batch packs blocks'
    survivor columns side by side — group g's segments stay consecutive
    inside a batch, so each block is a contiguous column range and the
    composite's zero blocks never materialize (reconstruct_block dispatches
    per-block ranges).  Same depth-N inflight deque, per-volume CRC fold,
    and triple overlap as `_rebuild_group`.

    Group-scoped failure isolation: a survivor-read failure marks ONLY that
    group failed — its later segments stop staging, its drains stop
    writing, its partials are unlinked, its members get the error — while
    every other block keeps flowing through the same dispatches.  Wholesale
    failures (decode/drain) raise to the caller, which unlinks everything.

    Returns ({base: [rebuilt shard ids]}, {base: error})."""
    encs = [members[0]["encoder"] for members in groups]
    base_enc = encs[0]
    max_k = max(e.data_shards for e in encs)
    align = max(int(getattr(e, "width_align", 1) or 1) for e in encs)
    chunks_per_batch = max(1, max_batch_bytes // (max_k * buffer_size))
    span = _aligned(chunks_per_batch * buffer_size, align)
    ring = _StagingRing(depth + 1, (max_k, span))
    flat = [(gi, job) for gi, members in enumerate(groups) for job in members]
    crcs = [{s: 0 for s in job["missing"]} for _, job in flat]
    failed: dict[int, str] = {}  # group index -> error string
    # width-packed segments, (group, member, shard offset, take); iterating
    # group-major keeps each group's columns consecutive within a batch
    batches: list[list[tuple[int, int, int, int]]] = []
    cur: list[tuple[int, int, int, int]] = []
    room = span
    for mi, (gi, job) in enumerate(flat):
        off = 0
        size = int(job["shard_size"])
        while off < size:
            take = min(room, size - off)
            cur.append((gi, mi, off, take))
            off += take
            room -= take
            if room == 0:
                batches.append(cur)
                cur, room = [], span
    if cur:
        batches.append(cur)
    with ExitStack() as stack:
        outs = [
            {
                s: stack.enter_context(open(shard_file_name(job["base"], s), "wb"))
                for s in job["missing"]
            }
            for _, job in flat
        ]
        inflight: deque = deque()  # FIFO of (handle, segments)

        def drain_one() -> None:
            lazy, segs = inflight.popleft()
            width = sum(t for _, _, _, t in segs)
            with trace_mod.span("rebuild.drain", width=width):
                dec = np.asarray(lazy)  # (max_m, span) — the sync point
                col = 0
                for gi, mi, off, length in segs:
                    if gi not in failed:
                        for k, s in enumerate(flat[mi][1]["missing"]):
                            row = dec[k, col : col + length]
                            outs[mi][s].write(row)
                            crcs[mi][s] = zlib.crc32(row, crcs[mi][s])
                    col += length

        def issue_prefetch(bi: int) -> None:
            if bi < len(batches):
                for gi, mi, off, length in batches[bi]:
                    if gi in failed:
                        continue
                    src = flat[mi][1]["sources"]
                    for s in flat[mi][1]["survivors"]:
                        src[s].prefetch(off, length)

        try:
            for j in range(min(ahead, len(batches))):
                issue_prefetch(j)
            for bi, segs in enumerate(batches):
                issue_prefetch(bi + ahead)
                while len(inflight) >= depth:
                    drain_one()
                width = sum(t for _, _, _, t in segs)
                blocks: list[dict] = []
                with trace_mod.span("rebuild.stage", batch=bi, width=width):
                    staging = ring.take()
                    col = 0
                    for gi, mi, off, length in segs:
                        job = flat[mi][1]
                        if gi not in failed:
                            try:
                                src = job["sources"]
                                for i, s in enumerate(job["survivors"]):
                                    src[s].read_into(off, staging[i, col : col + length])
                            except Exception as e:  # noqa: BLE001
                                failed[gi] = f"{type(e).__name__}: {e}"[:300]
                        if gi not in failed:
                            enc = encs[gi]
                            if blocks and blocks[-1]["_gi"] == gi:
                                blocks[-1]["width"] += length
                            else:
                                blocks.append({
                                    "_gi": gi,
                                    "encoder": enc,
                                    "survivors": job["survivors"],
                                    "wanted": job["missing"],
                                    "col_start": col,
                                    "width": length,
                                })
                        col += length
                # a read failure may land after its group's block opened:
                # drop any block of a now-failed group before dispatching
                blocks = [b for b in blocks if b["_gi"] not in failed]
                if blocks:
                    decoded = base_enc.reconstruct_block(staging, blocks)
                    inflight.append((decoded, segs))
            while inflight:
                drain_one()
        except BaseException:
            _discard_inflight(inflight)
            raise
    rebuilt: dict = {}
    errors: dict = {}
    for mi, (gi, job) in enumerate(flat):
        if gi in failed:
            for s in job["missing"]:
                try:
                    os.unlink(shard_file_name(job["base"], s))
                except OSError:
                    pass
            errors[job["base"]] = failed[gi]
            continue
        try:
            _verify_rebuilt_crcs(job["base"], crcs[mi])
        except Exception as e:  # noqa: BLE001 — per-volume verify failure
            # unlinks only that volume; the rest of the cohort is good
            for s in job["missing"]:
                try:
                    os.unlink(shard_file_name(job["base"], s))
                except OSError:
                    pass
            errors[job["base"]] = f"{type(e).__name__}: {e}"[:300]
            continue
        rebuilt[job["base"]] = list(job["missing"])
    return rebuilt, errors


def _rebuild_group(
    members: list[dict], depth: int, ahead: int, buffer_size: int,
    max_batch_bytes: int,
) -> None:
    """One same-signature group: a single depth-N pipeline whose batches
    pack columns from consecutive volumes (see rebuild_ec_files_batch)."""
    enc = members[0]["encoder"]
    survivors = list(members[0]["survivors"])
    missing = list(members[0]["missing"])
    align = int(getattr(enc, "width_align", 1) or 1)
    chunks_per_batch = max(1, max_batch_bytes // (enc.data_shards * buffer_size))
    span = _aligned(chunks_per_batch * buffer_size, align)
    ring = _StagingRing(depth + 1, (enc.data_shards, span))
    crcs = [{s: 0 for s in missing} for _ in members]
    # batches of width-packed segments: [(job index, offset, length), ...]
    batches: list[list[tuple[int, int, int]]] = []
    cur: list[tuple[int, int, int]] = []
    room = span
    for ji, job in enumerate(members):
        off = 0
        size = int(job["shard_size"])
        while off < size:
            take = min(room, size - off)
            cur.append((ji, off, take))
            off += take
            room -= take
            if room == 0:
                batches.append(cur)
                cur, room = [], span
    if cur:
        batches.append(cur)
    with ExitStack() as stack:
        outs = [
            {
                s: stack.enter_context(
                    open(shard_file_name(job["base"], s), "wb")
                )
                for s in missing
            }
            for job in members
        ]
        inflight: deque = deque()  # FIFO of (handle, segments, valid)

        def drain_one() -> None:
            lazy, segs, valid = inflight.popleft()
            with trace_mod.span("rebuild.drain", width=valid):
                dec = np.asarray(lazy)  # (len(missing), width) — sync point
                col = 0
                for ji, off, length in segs:
                    for k, s in enumerate(missing):
                        row = dec[k, col : col + length]
                        outs[ji][s].write(row)
                        crcs[ji][s] = zlib.crc32(row, crcs[ji][s])
                    col += length

        def issue_prefetch(bi: int) -> None:
            if bi < len(batches):
                for ji, off, length in batches[bi]:
                    src = members[ji]["sources"]
                    for s in survivors:
                        src[s].prefetch(off, length)

        try:
            for j in range(min(ahead, len(batches))):
                issue_prefetch(j)
            for bi, segs in enumerate(batches):
                issue_prefetch(bi + ahead)
                while len(inflight) >= depth:
                    drain_one()
                width = sum(length for _, _, length in segs)
                with trace_mod.span("rebuild.stage", batch=bi, width=width):
                    staging = ring.take()
                    col = 0
                    for ji, off, length in segs:
                        src = members[ji]["sources"]
                        for i, s in enumerate(survivors):
                            src[s].read_into(off, staging[i, col : col + length])
                        col += length
                    aw = _aligned(width, align)
                    if aw > width:
                        staging[:, width:aw] = 0  # pad columns are zeros
                decoded = enc.reconstruct_lazy(
                    staging[:, :aw], survivors, missing, donate=True
                )
                inflight.append((decoded, segs, width))
            while inflight:
                drain_one()
        except BaseException:
            _discard_inflight(inflight)
            raise
    for ji, job in enumerate(members):
        _verify_rebuilt_crcs(job["base"], crcs[ji])


def rebuild_ec_files(
    base_file_name: str,
    encoder: Optional[Encoder] = None,
    buffer_size: int = 4 * 1024 * 1024,
    max_batch_bytes: int = 64 * 1024 * 1024,
    pipeline_depth: Optional[int] = None,
) -> list[int]:
    """Reconstruct missing .ecNN files from >=10 survivors (RebuildEcFiles).

    The device-first repair path: each batch is one flat
    (survivors, width) slab — one contiguous read per survivor straight
    into a reused staging ring (no chunk transpose, no per-batch host
    allocation) decoded by ONE fused survivors->missing matrix in ONE
    device dispatch, with the same depth-N inflight pipeline as
    `_encode_rows`: up to `pipeline_depth` batches decode on-device while
    the next batch's slab reads run; drains are FIFO so rebuilt files
    receive bytes in order. Output is byte-identical to
    `rebuild_ec_files_serial` (zero-padding the tail slab is exact: GF
    matmul maps zero columns to zero columns, and the pad is trimmed
    before writing). Rebuilt shards' CRC32s are folded in as the bytes
    stream out and checked against the .eci-recorded values when present;
    a mid-stream failure (or CRC mismatch) drains inflight device work
    and unlinks the partial rebuilt files instead of leaking them.

    Returns the rebuilt shard ids."""
    enc = encoder or encoder_for_base(base_file_name)
    present, missing, shard_size = _check_rebuild_geometry(base_file_name, enc)
    if not missing:
        return []
    with ExitStack() as stack:
        sources = {
            s: stack.enter_context(LocalSlabSource(shard_file_name(base_file_name, s)))
            for s in present
        }
        return rebuild_ec_files_from_sources(
            base_file_name,
            sources,
            shard_size,
            encoder=enc,
            missing=missing,
            buffer_size=buffer_size,
            max_batch_bytes=max_batch_bytes,
            pipeline_depth=pipeline_depth,
        )


def _verify_rebuilt_crcs(base_file_name: str, crcs: dict) -> None:
    """Integrity gate on the rebuild output: when the volume's .eci recorded
    per-shard CRC32s at encode time, a rebuilt shard whose streaming CRC
    disagrees means a silently-corrupt survivor (or a decode bug) produced
    garbage — fail the rebuild rather than ship a wrong shard."""
    info = read_ec_info(base_file_name)
    recorded = (info or {}).get("shard_crc32")
    want_len = geometry_from_info(info).total_shards
    if not isinstance(recorded, list) or len(recorded) != want_len:
        return
    bad = {s: (c, recorded[s]) for s, c in crcs.items() if c != recorded[s]}
    if bad:
        raise IOError(
            f"rebuilt shard CRC mismatch vs .eci record: "
            f"{{shard: (got, want)}} = {bad} — corrupt survivor?"
        )


def rebuild_ec_files_serial(
    base_file_name: str,
    encoder: Optional[Encoder] = None,
    buffer_size: int = 4 * 1024 * 1024,
) -> list[int]:
    """The pre-pipeline serial rebuild: one blocking reconstruct per chunk.
    Kept as the correctness oracle (bench golden path + byte-identity
    tests) and the shape the AVX2-baseline comparison is defined against."""
    enc = encoder or encoder_for_base(base_file_name)
    present, missing, shard_size = _check_rebuild_geometry(base_file_name, enc)
    if not missing:
        return []
    with ExitStack() as stack:
        ins = {
            s: stack.enter_context(open(shard_file_name(base_file_name, s), "rb"))
            for s in present
        }
        outs = {
            s: stack.enter_context(open(shard_file_name(base_file_name, s), "wb"))
            for s in missing
        }
        for off in range(0, shard_size, buffer_size):
            n = min(buffer_size, shard_size - off)
            shards: list[Optional[np.ndarray]] = [None] * enc.total_shards
            for s in present:
                shards[s] = read_padded(ins[s], off, n)
            rec = enc.reconstruct(shards, wanted=missing)
            for s in missing:
                outs[s].write(np.ascontiguousarray(rec[s]))  # buffer-protocol write
    return missing


def write_dat_file(
    base_file_name: str,
    dat_file_size: Optional[int] = None,
    large_block_size: int = ERASURE_CODING_LARGE_BLOCK_SIZE,
    small_block_size: int = ERASURE_CODING_SMALL_BLOCK_SIZE,
) -> None:
    """Data shards -> <base>.dat (WriteDatFile / ec.decode semantics).

    Recorded .eci geometry (block sizes AND shard counts) overrides the
    arguments — decoding with the wrong layout would interleave garbage
    silently."""
    info = read_ec_info(base_file_name)
    if info is not None:
        large_block_size = info["large_block_size"]
        small_block_size = info["small_block_size"]
        if dat_file_size is None:
            dat_file_size = info["dat_size"]
    if dat_file_size is None:
        raise ValueError("dat_file_size required when no .eci sidecar exists")
    data_shards = geometry_from_info(info).data_shards
    n_large, _ = stripe_layout(
        dat_file_size, large_block_size, small_block_size, data_shards
    )

    # stage under a dot-tmp name: serving paths discover <base>.dat by
    # existence, so a crash mid-decode must never leave a torn .dat there
    tmp_dat = base_file_name + ".dat.tmp"
    with ExitStack() as stack:
        # no-op after the publishing replace; reaps the stage on any failure
        stack.callback(
            lambda: os.path.exists(tmp_dat) and os.remove(tmp_dat)
        )
        ins = [
            stack.enter_context(open(shard_file_name(base_file_name, s), "rb"))
            for s in range(data_shards)
        ]
        out = stack.enter_context(open(tmp_dat, "wb"))
        written = 0
        # large rows
        for row in range(n_large):
            for d in range(data_shards):
                ins[d].seek(row * large_block_size)
                out.write(ins[d].read(large_block_size))
                written += large_block_size
        # small rows
        small_start = n_large * large_block_size
        row = 0
        while written < dat_file_size:
            row_progress = 0
            for d in range(data_shards):
                if written >= dat_file_size:
                    break
                ins[d].seek(small_start + row * small_block_size)
                chunk = ins[d].read(small_block_size)
                take = min(len(chunk), dat_file_size - written)
                out.write(chunk[:take])
                written += take
                row_progress += take
            if row_progress == 0:
                raise IOError(
                    f"shards exhausted at {written} bytes but dat_file_size says "
                    f"{dat_file_size} — truncated shards or stale size"
                )
            row += 1
        out.flush()
        os.fsync(out.fileno())
        os.replace(tmp_dat, base_file_name + ".dat")


def write_idx_file_from_ec_index(base_file_name: str) -> None:
    """<base>.ecx + <base>.ecj -> <base>.idx (WriteIdxFileFromEcIndex):
    copy sorted entries, then append a tombstone per journaled deletion.
    Entries already tombstoned in .ecx (by compact_ecj) are normalized to
    the same (key, 0, -1) shape a journal replay would have appended."""
    with open(base_file_name + ".ecx", "rb") as f:
        ecx = f.read()
    entries = list(idx_mod.walk_index_buffer(ecx))
    deleted = read_ecj(base_file_name)
    tmp_idx = base_file_name + ".idx.tmp"
    with open(tmp_idx, "wb") as out:
        for key, off, size in entries:
            if types.is_deleted(size):
                out.write(types.pack_index_entry(key, 0, types.TOMBSTONE_FILE_SIZE))
            else:
                out.write(types.pack_index_entry(key, off, size))
        for key in deleted:
            out.write(types.pack_index_entry(key, 0, types.TOMBSTONE_FILE_SIZE))
        out.flush()
        os.fsync(out.fileno())
    os.replace(tmp_idx, base_file_name + ".idx")


# -- .ecj deletion journal ---------------------------------------------------


def append_ecj(base_file_name: str, needle_id: int) -> None:
    """Journal one EC deletion, fsync'd: an acked EC delete must survive a
    power cut (the .ecj is the ONLY record of it until compact_ecj folds
    the journal — same flush+fsync discipline kernel_sweep's --out uses).
    A crash mid-append can still leave a torn tail record; read_ecj
    ignores it, so the worst a torn append costs is the un-acked delete."""
    with open(base_file_name + ".ecj", "ab") as f:
        f.write(needle_id.to_bytes(types.NEEDLE_ID_SIZE, "big"))
        f.flush()
        os.fsync(f.fileno())


def read_ecj(base_file_name: str) -> list[int]:
    path = base_file_name + ".ecj"
    if not os.path.exists(path):
        return []
    with open(path, "rb") as f:
        buf = f.read()
    # // drops a torn tail record (crash mid-append): every COMPLETE entry
    # replays, the partial one is noise, never a mis-parsed needle id
    n = len(buf) // types.NEEDLE_ID_SIZE
    return [
        int.from_bytes(buf[i * 8 : i * 8 + 8], "big") for i in range(n)
    ]


def compact_ecj(base_file_name: str) -> int:
    """Fold the deletion journal into the index (the reference compacts the
    .ecj on mount so a delete-heavy EC volume's journal doesn't grow without
    bound [ref: weed/storage/erasure_coding ecj replay/compact; SURVEY §5]):
    tombstone every journaled id in .ecx, then drop .ecj.

    Crash-safe ordering: write .ecx.cpt -> fsync -> rename over .ecx ->
    unlink .ecj. A crash before the rename leaves both files untouched; a
    crash after it leaves a stale .ecj whose replay only re-tombstones
    already-dead entries — idempotent either way. Returns the number of
    journal entries folded."""
    deleted = set(read_ecj(base_file_name))
    if not deleted:
        return 0
    ecx = base_file_name + ".ecx"
    with open(ecx, "rb") as f:
        buf = f.read()
    tmp = ecx + ".cpt"
    with open(tmp, "wb") as out:
        for key, off, size in idx_mod.walk_index_buffer(buf):
            if key in deleted and not types.is_deleted(size):
                size = types.TOMBSTONE_FILE_SIZE
            out.write(types.pack_index_entry(key, off, size))
        out.flush()
        os.fsync(out.fileno())
    os.replace(tmp, ecx)
    try:
        os.remove(base_file_name + ".ecj")
    except FileNotFoundError:
        pass
    return len(deleted)
