"""Stripe engine — file-level EC encode/decode/rebuild with the exact layout
semantics of weed/storage/erasure_coding/ec_encoder.go + ec_decoder.go
[VERIFY: mount empty; upstream semantics per SURVEY.md §2.3].

Layout: a volume .dat is processed as block rows. While more than one full
large row (DATA_SHARDS x large_block) remains, encode large rows; the tail is
encoded as small rows, the last one zero-padded past EOF. Shard k's .ec{k:02d}
file is the concatenation of its column across rows. All 14 shard files end up
the same length.

TPU-first deviation from the reference's inner loop: the reference encodes
256 KiB buffer segments one at a time per goroutine; here segments are stacked
into a (batch, shards, seg) tensor and dispatched as ONE device call per
batch so the MXU sees large matmuls (SURVEY.md §2.5 pipeline analog). The
on-disk output is byte-identical either way.
"""

from __future__ import annotations

import json
import os
from contextlib import ExitStack
from typing import Optional, Sequence

import numpy as np

from seaweedfs_tpu.ec.constants import (
    DATA_SHARDS_COUNT,
    EC_BUFFER_SIZE,
    ERASURE_CODING_LARGE_BLOCK_SIZE,
    ERASURE_CODING_SMALL_BLOCK_SIZE,
    TOTAL_SHARDS_COUNT,
)
from seaweedfs_tpu.ops.rs_codec import Encoder, new_encoder
from seaweedfs_tpu.storage import idx as idx_mod
from seaweedfs_tpu.storage import types
from seaweedfs_tpu.storage.needle_map import MemDb


def to_ext(shard_id: int) -> str:
    return f".ec{shard_id:02d}"


def shard_file_name(base_file_name: str, shard_id: int) -> str:
    return base_file_name + to_ext(shard_id)


def read_padded(f, offset: int, length: int) -> np.ndarray:
    """Read `length` bytes at `offset`, zero-padding past EOF."""
    f.seek(offset)
    raw = f.read(length)
    buf = np.zeros(length, dtype=np.uint8)
    if raw:
        buf[: len(raw)] = np.frombuffer(raw, dtype=np.uint8)
    return buf


def _encode_rows(
    f,
    enc: Encoder,
    outputs: Sequence,
    start_offset: int,
    block_size: int,
    n_rows: int,
    buffer_size: int,
    max_batch_bytes: int,
) -> None:
    """Encode `n_rows` rows of `block_size` blocks, batching segments into
    single device calls. Output files receive bytes in row-major order."""
    if buffer_size > block_size:
        buffer_size = block_size
    if block_size % buffer_size:
        raise ValueError(f"block size {block_size} not a multiple of buffer {buffer_size}")
    segs_per_row = block_size // buffer_size
    # how many (10 x buffer) segments fit the device-batch budget
    batch_cap = max(1, max_batch_bytes // (DATA_SHARDS_COUNT * buffer_size))
    # iterate segments in global order (row-major, then segment within block)
    pending: list[tuple[int, int]] = []  # (row, seg)
    # one-deep pipeline (SURVEY §7.1 double buffering): batch N's parity
    # computes on-device (async dispatch) while batch N+1's disk reads run;
    # the np.asarray in drain() is the synchronization point
    inflight: list[tuple[np.ndarray, object]] = []  # [(data, parity_handle)]

    def drain() -> None:
        if not inflight:
            return
        data, parity = inflight.pop()
        parity_np = np.asarray(parity)
        if DATA_SHARDS_COUNT + parity_np.shape[1] != len(outputs):
            # a geometry-mismatched encoder must fail loudly, not leave
            # trailing .ecNN files silently empty
            raise ValueError(
                f"encoder produced {parity_np.shape[1]} parity shards; "
                f"layout wants {len(outputs) - DATA_SHARDS_COUNT}"
            )
        for bi in range(data.shape[0]):
            for s in range(DATA_SHARDS_COUNT):
                # contiguous row views write via the buffer protocol —
                # no tobytes() copy per (batch, shard)
                outputs[s].write(data[bi, s])
            for p in range(parity_np.shape[1]):
                outputs[DATA_SHARDS_COUNT + p].write(parity_np[bi, p])

    def flush(batch: list[tuple[int, int]]):
        if not batch:
            return
        data = np.empty((len(batch), DATA_SHARDS_COUNT, buffer_size), dtype=np.uint8)
        # read runs of consecutive segments as one contiguous slab per shard
        # (10 large sequential reads per row-run instead of one seek per
        # segment x shard — keeps readahead alive at 1 GiB block strides)
        i = 0
        while i < len(batch):
            row, seg0 = batch[i]
            j = i
            while j + 1 < len(batch) and batch[j + 1] == (row, batch[j][1] + 1):
                j += 1
            nseg = j - i + 1
            row_start = start_offset + row * block_size * DATA_SHARDS_COUNT
            for d in range(DATA_SHARDS_COUNT):
                slab = read_padded(
                    f, row_start + d * block_size + seg0 * buffer_size, nseg * buffer_size
                )
                data[i : j + 1, d] = slab.reshape(nseg, buffer_size)
            i = j + 1
        parity = enc.encode_parity_lazy(data)  # async: returns pre-compute
        drain()  # materialize + write the PREVIOUS batch while this one runs
        inflight.append((data, parity))

    for row in range(n_rows):
        for seg in range(segs_per_row):
            pending.append((row, seg))
            if len(pending) >= batch_cap:
                flush(pending)
                pending = []
    flush(pending)
    drain()


def write_ec_files(
    base_file_name: str,
    large_block_size: int = ERASURE_CODING_LARGE_BLOCK_SIZE,
    small_block_size: int = ERASURE_CODING_SMALL_BLOCK_SIZE,
    buffer_size: int = EC_BUFFER_SIZE,
    encoder: Optional[Encoder] = None,
    max_batch_bytes: int = 64 * 1024 * 1024,
) -> None:
    """<base>.dat -> <base>.ec00 .. .ec13 (WriteEcFiles semantics)."""
    enc = encoder or new_encoder()
    dat_path = base_file_name + ".dat"
    dat_size = os.path.getsize(dat_path)
    large_row = large_block_size * DATA_SHARDS_COUNT
    small_row = small_block_size * DATA_SHARDS_COUNT

    n_large = 0
    remaining = dat_size
    while remaining > large_row:
        n_large += 1
        remaining -= large_row
    n_small = 0
    while remaining > 0:
        n_small += 1
        remaining -= small_row

    with ExitStack() as stack:
        f = stack.enter_context(open(dat_path, "rb"))
        outputs = [
            stack.enter_context(open(shard_file_name(base_file_name, s), "wb"))
            for s in range(TOTAL_SHARDS_COUNT)
        ]
        _encode_rows(f, enc, outputs, 0, large_block_size, n_large, buffer_size, max_batch_bytes)
        _encode_rows(
            f,
            enc,
            outputs,
            n_large * large_row,
            small_block_size,
            n_small,
            min(buffer_size, small_block_size),
            max_batch_bytes,
        )
    write_ec_info(base_file_name, large_block_size, small_block_size, dat_size)


def write_ec_info(
    base_file_name: str, large_block_size: int, small_block_size: int, dat_size: int
) -> None:
    """Record the stripe geometry + true .dat size in an .eci sidecar.

    The reference needs no such file because its block sizes are compile-time
    constants; here they are parameters (tests use scaled-down geometry), and
    opening a shard set with the wrong geometry would silently mis-map
    intervals. Shard sets written by stock tooling (no .eci) still open fine
    with the default constants."""
    tmp = base_file_name + ".eci.tmp"
    with open(tmp, "w") as f:
        json.dump(
            {
                "large_block_size": large_block_size,
                "small_block_size": small_block_size,
                "dat_size": dat_size,
            },
            f,
        )
    os.replace(tmp, base_file_name + ".eci")


_ECI_KEYS = ("large_block_size", "small_block_size", "dat_size")


def read_ec_info(base_file_name: str) -> Optional[dict]:
    try:
        with open(base_file_name + ".eci") as f:
            info = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(info, dict) or not all(
        isinstance(info.get(k), int) for k in _ECI_KEYS
    ):
        return None
    return info


def write_sorted_file_from_idx(base_file_name: str, ext: str = ".ecx") -> None:
    """<base>.idx -> <base>.ecx: replay the index log, write entries sorted
    by needle id (WriteSortedFileFromIdx semantics)."""
    db = MemDb()
    db.load_from_idx(base_file_name + ".idx")
    db.save_to_idx(base_file_name + ext)


def generate_ec_files(
    base_file_name: str,
    **kwargs,
) -> None:
    """The VolumeEcShardsGenerate work: shards + sorted index."""
    write_ec_files(base_file_name, **kwargs)
    write_sorted_file_from_idx(base_file_name)


def find_local_shards(base_file_name: str) -> list[int]:
    return [
        s for s in range(TOTAL_SHARDS_COUNT) if os.path.exists(shard_file_name(base_file_name, s))
    ]


def _check_rebuild_geometry(base_file_name: str) -> tuple[list[int], list[int], int]:
    """Shared preflight for both rebuild paths: -> (present, missing,
    shard_size). Raises when fewer than DATA_SHARDS survive or survivors
    disagree on length (truncated shard)."""
    present = find_local_shards(base_file_name)
    missing = [s for s in range(TOTAL_SHARDS_COUNT) if s not in present]
    if not missing:
        return present, missing, 0
    if len(present) < DATA_SHARDS_COUNT:
        raise ValueError(
            f"cannot rebuild: only {len(present)} shards present, need {DATA_SHARDS_COUNT}"
        )
    sizes = {s: os.path.getsize(shard_file_name(base_file_name, s)) for s in present}
    if len(set(sizes.values())) != 1:
        raise IOError(f"surviving shards disagree on length: {sizes} — truncated shard?")
    return present, missing, sizes[present[0]]


def rebuild_ec_files(
    base_file_name: str,
    encoder: Optional[Encoder] = None,
    buffer_size: int = 4 * 1024 * 1024,
    max_batch_bytes: int = 64 * 1024 * 1024,
) -> list[int]:
    """Reconstruct missing .ecNN files from >=10 survivors (RebuildEcFiles).

    The device-first repair path: chunks are stacked into a
    (batch, survivors, buffer) tensor and decoded by ONE fused
    survivors->missing matrix in ONE device dispatch per batch (not per
    chunk), with the same one-deep inflight pipeline as `_encode_rows` —
    batch N decodes on-device (async dispatch) while batch N+1's slab
    reads run; the np.asarray in drain() is the synchronization point.
    Reads are one contiguous slab per survivor per batch, so disk
    readahead stays alive. Output is byte-identical to
    `rebuild_ec_files_serial` (zero-padding the tail chunk is exact: GF
    matmul maps zero columns to zero columns, and the pad is trimmed
    before writing).

    Returns the rebuilt shard ids."""
    enc = encoder or new_encoder()
    present, missing, shard_size = _check_rebuild_geometry(base_file_name)
    if not missing:
        return []
    # first DATA_SHARDS present ids, exactly like Encoder._pick_survivors —
    # the serial path and this one must derive the SAME decode matrix
    survivors = present[:DATA_SHARDS_COUNT]
    chunks_per_batch = max(1, max_batch_bytes // (DATA_SHARDS_COUNT * buffer_size))
    span = chunks_per_batch * buffer_size
    with ExitStack() as stack:
        ins = {
            s: stack.enter_context(open(shard_file_name(base_file_name, s), "rb"))
            for s in survivors
        }
        outs = {
            s: stack.enter_context(open(shard_file_name(base_file_name, s), "wb"))
            for s in missing
        }
        inflight: list[tuple[object, int]] = []  # [(decoded_handle, valid_bytes)]

        def drain() -> None:
            if not inflight:
                return
            lazy, valid = inflight.pop()
            out = np.asarray(lazy)  # (B, len(missing), buffer) — sync point
            for k, s in enumerate(missing):
                # contiguous view writes via the buffer protocol; the tail
                # batch trims its zero-pad back off
                outs[s].write(np.ascontiguousarray(out[:, k, :]).reshape(-1)[:valid])

        for off in range(0, shard_size, span):
            valid = min(span, shard_size - off)
            nchunks = -(-valid // buffer_size)
            data = np.empty((DATA_SHARDS_COUNT, nchunks * buffer_size), dtype=np.uint8)
            for i, s in enumerate(survivors):
                data[i] = read_padded(ins[s], off, nchunks * buffer_size)
            chunked = np.ascontiguousarray(
                data.reshape(DATA_SHARDS_COUNT, nchunks, buffer_size).transpose(1, 0, 2)
            )
            decoded = enc.reconstruct_lazy(chunked, survivors, missing)  # async
            drain()  # materialize + write the PREVIOUS batch while this one runs
            inflight.append((decoded, valid))
        drain()
    return missing


def rebuild_ec_files_serial(
    base_file_name: str,
    encoder: Optional[Encoder] = None,
    buffer_size: int = 4 * 1024 * 1024,
) -> list[int]:
    """The pre-pipeline serial rebuild: one blocking reconstruct per chunk.
    Kept as the correctness oracle (bench golden path + byte-identity
    tests) and the shape the AVX2-baseline comparison is defined against."""
    enc = encoder or new_encoder()
    present, missing, shard_size = _check_rebuild_geometry(base_file_name)
    if not missing:
        return []
    with ExitStack() as stack:
        ins = {
            s: stack.enter_context(open(shard_file_name(base_file_name, s), "rb"))
            for s in present
        }
        outs = {
            s: stack.enter_context(open(shard_file_name(base_file_name, s), "wb"))
            for s in missing
        }
        for off in range(0, shard_size, buffer_size):
            n = min(buffer_size, shard_size - off)
            shards: list[Optional[np.ndarray]] = [None] * TOTAL_SHARDS_COUNT
            for s in present:
                shards[s] = read_padded(ins[s], off, n)
            rec = enc.reconstruct(shards, wanted=missing)
            for s in missing:
                outs[s].write(np.ascontiguousarray(rec[s]))  # buffer-protocol write
    return missing


def write_dat_file(
    base_file_name: str,
    dat_file_size: Optional[int] = None,
    large_block_size: int = ERASURE_CODING_LARGE_BLOCK_SIZE,
    small_block_size: int = ERASURE_CODING_SMALL_BLOCK_SIZE,
) -> None:
    """Data shards -> <base>.dat (WriteDatFile / ec.decode semantics).

    Recorded .eci geometry overrides the arguments — decoding with the wrong
    block sizes would interleave garbage silently."""
    info = read_ec_info(base_file_name)
    if info is not None:
        large_block_size = info["large_block_size"]
        small_block_size = info["small_block_size"]
        if dat_file_size is None:
            dat_file_size = info["dat_size"]
    if dat_file_size is None:
        raise ValueError("dat_file_size required when no .eci sidecar exists")
    large_row = large_block_size * DATA_SHARDS_COUNT
    n_large = 0
    remaining = dat_file_size
    while remaining > large_row:
        n_large += 1
        remaining -= large_row

    with ExitStack() as stack:
        ins = [
            stack.enter_context(open(shard_file_name(base_file_name, s), "rb"))
            for s in range(DATA_SHARDS_COUNT)
        ]
        out = stack.enter_context(open(base_file_name + ".dat", "wb"))
        written = 0
        # large rows
        for row in range(n_large):
            for d in range(DATA_SHARDS_COUNT):
                ins[d].seek(row * large_block_size)
                out.write(ins[d].read(large_block_size))
                written += large_block_size
        # small rows
        small_start = n_large * large_block_size
        row = 0
        while written < dat_file_size:
            row_progress = 0
            for d in range(DATA_SHARDS_COUNT):
                if written >= dat_file_size:
                    break
                ins[d].seek(small_start + row * small_block_size)
                chunk = ins[d].read(small_block_size)
                take = min(len(chunk), dat_file_size - written)
                out.write(chunk[:take])
                written += take
                row_progress += take
            if row_progress == 0:
                raise IOError(
                    f"shards exhausted at {written} bytes but dat_file_size says "
                    f"{dat_file_size} — truncated shards or stale size"
                )
            row += 1


def write_idx_file_from_ec_index(base_file_name: str) -> None:
    """<base>.ecx + <base>.ecj -> <base>.idx (WriteIdxFileFromEcIndex):
    copy sorted entries, then append a tombstone per journaled deletion.
    Entries already tombstoned in .ecx (by compact_ecj) are normalized to
    the same (key, 0, -1) shape a journal replay would have appended."""
    with open(base_file_name + ".ecx", "rb") as f:
        ecx = f.read()
    entries = list(idx_mod.walk_index_buffer(ecx))
    deleted = read_ecj(base_file_name)
    with open(base_file_name + ".idx", "wb") as out:
        for key, off, size in entries:
            if types.is_deleted(size):
                out.write(types.pack_index_entry(key, 0, types.TOMBSTONE_FILE_SIZE))
            else:
                out.write(types.pack_index_entry(key, off, size))
        for key in deleted:
            out.write(types.pack_index_entry(key, 0, types.TOMBSTONE_FILE_SIZE))


# -- .ecj deletion journal ---------------------------------------------------


def append_ecj(base_file_name: str, needle_id: int) -> None:
    with open(base_file_name + ".ecj", "ab") as f:
        f.write(needle_id.to_bytes(types.NEEDLE_ID_SIZE, "big"))


def read_ecj(base_file_name: str) -> list[int]:
    path = base_file_name + ".ecj"
    if not os.path.exists(path):
        return []
    with open(path, "rb") as f:
        buf = f.read()
    n = len(buf) // types.NEEDLE_ID_SIZE
    return [
        int.from_bytes(buf[i * 8 : i * 8 + 8], "big") for i in range(n)
    ]


def compact_ecj(base_file_name: str) -> int:
    """Fold the deletion journal into the index (the reference compacts the
    .ecj on mount so a delete-heavy EC volume's journal doesn't grow without
    bound [ref: weed/storage/erasure_coding ecj replay/compact; SURVEY §5]):
    tombstone every journaled id in .ecx, then drop .ecj.

    Crash-safe ordering: write .ecx.cpt -> fsync -> rename over .ecx ->
    unlink .ecj. A crash before the rename leaves both files untouched; a
    crash after it leaves a stale .ecj whose replay only re-tombstones
    already-dead entries — idempotent either way. Returns the number of
    journal entries folded."""
    deleted = set(read_ecj(base_file_name))
    if not deleted:
        return 0
    ecx = base_file_name + ".ecx"
    with open(ecx, "rb") as f:
        buf = f.read()
    tmp = ecx + ".cpt"
    with open(tmp, "wb") as out:
        for key, off, size in idx_mod.walk_index_buffer(buf):
            if key in deleted and not types.is_deleted(size):
                size = types.TOMBSTONE_FILE_SIZE
            out.write(types.pack_index_entry(key, off, size))
        out.flush()
        os.fsync(out.fileno())
    os.replace(tmp, ecx)
    try:
        os.remove(base_file_name + ".ecj")
    except FileNotFoundError:
        pass
    return len(deleted)
