"""Scrub & self-heal — continuous shard integrity scanning.

The per-shard CRC32s the streaming encode records in `.eci` (and rebuilds
verify on write) are only worth anything if something READS them before a
second failure makes a corrupt shard unrecoverable. This module is that
something: a background scrubber per volume server walks every mounted EC
shard in bounded chunks, folds CRC32 as it goes, and compares the result
against the `.eci` record — bit rot, torn writes, truncated files, and
vanished shard files all surface as typed findings long before a rebuild
would happen to stream the bad bytes.

Design constraints, in order:

  1. **Never starve serving.** Every chunk read first takes a token from
     the caller-supplied admission hook (the volume server passes its
     PR-6 rebuild lane, `WEEDTPU_REBUILD_MAX_INFLIGHT` semantics), and the
     scan rate is capped (`WEEDTPU_SCRUB_RATE_MB`) — a scrub is repair
     traffic and queues behind foreground reads exactly like a rebuild
     slab stream does.
  2. **Survive restarts.** Progress lives in a fsync'd cursor file
     (volume, shard, offset, running CRC — CRC32 is resumable, so a
     restart continues mid-shard instead of rescanning terabytes), along
     with the quarantine entries whose repairs were still pending.
  3. **Report, don't act.** The scrubber only CLASSIFIES
     (ok/corrupt/truncated/missing) and hands findings to the injected
     callback; quarantine + repair policy live in the volume server,
     which owns the serving handles and the rebuild machinery.

Shard files are immutable once mounted (delta updates only ever touch
pre-seal `.inp` partials; rebuilds write fresh files then mount), so an
incremental scan with a persisted mid-shard cursor can never race a
legitimate writer — any mismatch is damage, not churn.
"""

from __future__ import annotations

import json
import os
import threading
import time
import zlib
from typing import Callable, Iterable, Optional

from seaweedfs_tpu import stats
from seaweedfs_tpu.ec import stripe
from seaweedfs_tpu.obs import trace as trace_mod


#: finding classes — the detection taxonomy the counters/quarantine use
OK = "ok"
CORRUPT = "corrupt"          # bytes present, CRC32 disagrees with .eci
TRUNCATED = "truncated"      # file shorter than the stripe geometry demands
MISSING = "missing"          # mounted shard whose file vanished underneath
UNVERIFIABLE = "unverifiable"  # volume predates CRC recording (no .eci CRCs)

FINDING_CLASSES = (CORRUPT, TRUNCATED, MISSING)


def expected_shard_size(info: dict) -> int:
    """Byte length every shard file of this volume must have, from the
    recorded `.eci` geometry: the ONE stripe-layout definition
    (stripe.stripe_layout) decides large/small row counts, so scrub,
    encode, and rebuild can never disagree about where EOF belongs."""
    n_large, n_small = stripe.stripe_layout(
        int(info["dat_size"]),
        int(info["large_block_size"]),
        int(info["small_block_size"]),
        stripe.geometry_from_info(info).data_shards,
    )
    return n_large * int(info["large_block_size"]) + n_small * int(
        info["small_block_size"]
    )


def scan_shard_file(
    path: str,
    want_crc: int,
    want_size: int,
    chunk_bytes: int = 4 * 1024 * 1024,
    offset: int = 0,
    crc: int = 0,
    budget: Optional[Callable[[int], None]] = None,
) -> str:
    """One full (or cursor-resumed) CRC pass over a shard file -> verdict.
    `budget(n)` is called before each chunk read with the chunk size about
    to be read — the rate limiter / admission hook; it may block. Size is
    checked FIRST so truncation classifies as truncation, not as the CRC
    mismatch it would also cause."""
    try:
        size = os.path.getsize(path)
    except OSError:
        return MISSING
    if size < want_size:
        return TRUNCATED
    if size > want_size:
        # longer than the geometry allows: bytes were appended or the
        # .eci lies — either way the shard cannot be vouched for
        return CORRUPT
    try:
        with open(path, "rb") as f:
            f.seek(offset)
            pos = offset
            while pos < want_size:
                n = min(chunk_bytes, want_size - pos)
                if budget is not None:
                    budget(n)
                chunk = f.read(n)
                if len(chunk) != n:
                    return TRUNCATED  # shrank mid-scan
                crc = zlib.crc32(chunk, crc)
                pos += n
    except OSError:
        return MISSING
    return OK if crc == (want_crc & 0xFFFFFFFF) else CORRUPT


class ScrubCursor:
    """Fsync'd scrub progress + pending-quarantine persistence.

    One JSON file: {"vid", "shard", "offset", "crc", "cycles",
    "quarantine": [{"vid", "shard", "reason"}, ...]}. The (offset, crc)
    pair makes mid-shard resume exact — CRC32 is a running fold, so the
    restart continues from byte `offset` with the saved accumulator
    instead of rescanning the prefix. Torn/garbage files load as a fresh
    cursor (scrub restarts from the top; never worse than no cursor)."""

    def __init__(self, path: str):
        self.path = path
        self.vid = 0
        self.shard = 0
        self.offset = 0
        self.crc = 0
        self.cycles = 0
        #: quarantine entries whose repair had not completed at save time —
        #: a restarted server re-enqueues these instead of forgetting that
        #: a shard it no longer mounts is sitting corrupt on its disk
        self.quarantine: list[dict] = []
        self._dirty = False
        self.load()

    def load(self) -> None:
        try:
            with open(self.path, encoding="utf-8") as f:
                d = json.load(f)
            self.vid = int(d.get("vid", 0))
            self.shard = int(d.get("shard", 0))
            self.offset = int(d.get("offset", 0))
            self.crc = int(d.get("crc", 0))
            self.cycles = int(d.get("cycles", 0))
            self.quarantine = [
                {
                    "vid": int(q["vid"]),
                    "shard": int(q["shard"]),
                    "reason": str(q.get("reason", CORRUPT)),
                }
                for q in d.get("quarantine", [])
                if isinstance(q, dict) and "vid" in q and "shard" in q
            ]
        except (OSError, ValueError, KeyError, TypeError):
            self.vid = self.shard = self.offset = self.crc = self.cycles = 0
            self.quarantine = []

    def save(self) -> None:
        tmp = self.path + ".tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(
                    {
                        "vid": self.vid,
                        "shard": self.shard,
                        "offset": self.offset,
                        "crc": self.crc,
                        "cycles": self.cycles,
                        "quarantine": self.quarantine,
                    },
                    f,
                )
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
        except OSError:
            # cursor persistence is best-effort: a failed save costs a
            # rescan after restart, never correctness
            try:
                os.unlink(tmp)
            except OSError:
                pass
        self._dirty = False

    def point(self, vid: int, shard: int, offset: int, crc: int) -> None:
        self.vid, self.shard, self.offset, self.crc = vid, shard, offset, crc
        self._dirty = True

    def add_quarantine(self, vid: int, shard: int, reason: str) -> None:
        ent = {"vid": int(vid), "shard": int(shard), "reason": str(reason)}
        if not any(
            q["vid"] == ent["vid"] and q["shard"] == ent["shard"]
            for q in self.quarantine
        ):
            self.quarantine.append(ent)
        self.save()  # quarantine entries are load-bearing: persist NOW

    def remove_quarantine(self, vid: int, shard: int) -> None:
        before = len(self.quarantine)
        self.quarantine = [
            q
            for q in self.quarantine
            if not (q["vid"] == int(vid) and q["shard"] == int(shard))
        ]
        if len(self.quarantine) != before:
            self.save()


class RepairPolicy:
    """Capped, backed-off repair scheduling for quarantined shards.

    `due(key)` answers whether a repair attempt may run now;
    `failed(key)` doubles that key's backoff (decorrelated by attempt
    count, capped at `max_backoff`); `succeeded(key)` forgets it. The
    CONCURRENCY cap lives in the caller's semaphore — this class only
    owns the per-shard retry clock, so it stays trivially testable."""

    def __init__(self, base: float = 5.0, max_backoff: float = 60.0,
                 time_fn: Callable[[], float] = time.monotonic):
        self.base = float(base)
        self.max_backoff = float(max_backoff)
        self._time = time_fn
        self._state: dict[tuple, tuple[int, float]] = {}  # key -> (attempts, next_ok)
        self._lock = threading.Lock()

    def due(self, key: tuple) -> bool:
        with self._lock:
            st = self._state.get(key)
            return st is None or self._time() >= st[1]

    def delay(self, key: tuple) -> float:
        """Seconds until `key` is due again (0 when due now)."""
        with self._lock:
            st = self._state.get(key)
            if st is None:
                return 0.0
            return max(0.0, st[1] - self._time())

    def failed(self, key: tuple) -> float:
        with self._lock:
            attempts = self._state.get(key, (0, 0.0))[0] + 1
            backoff = min(self.max_backoff, self.base * (2 ** (attempts - 1)))
            self._state[key] = (attempts, self._time() + backoff)
            return backoff

    def succeeded(self, key: tuple) -> None:
        with self._lock:
            self._state.pop(key, None)


class Scrubber:
    """The background integrity scanner for one volume server.

    `volumes()` must return a {vid: EcVolume} snapshot of currently-mounted
    EC volumes; `on_finding(vid, shard, verdict)` is called (from the
    scrub thread) for every non-ok shard — quarantine/repair policy is the
    caller's. `admit()` is the shared-lane hook: called before each chunk
    read, returns True to proceed or False to yield (the scrubber then
    sleeps briefly and retries — foreground traffic owns the lane)."""

    def __init__(
        self,
        volumes: Callable[[], dict],
        on_finding: Callable[[int, int, str], None],
        cursor_path: str,
        rate_mb: float = 64.0,
        chunk_bytes: int = 4 * 1024 * 1024,
        interval: float = 30.0,
        admit: Optional[Callable[[], bool]] = None,
        cursor_flush_bytes: int = 256 * 1024 * 1024,
        cursor: Optional[ScrubCursor] = None,
    ):
        self._volumes = volumes
        self._on_finding = on_finding
        # the caller may share a cursor it already owns (the volume server
        # keeps ONE quarantine ledger whether or not the scan thread runs)
        self.cursor = cursor if cursor is not None else ScrubCursor(cursor_path)
        self.rate_mb = float(rate_mb)
        self.chunk_bytes = max(64 * 1024, int(chunk_bytes))
        self.interval = float(interval)
        self._admit = admit
        self._cursor_flush = max(self.chunk_bytes, int(cursor_flush_bytes))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        #: scan-session pacing state for the rate cap
        self._window_t0 = time.monotonic()
        self._window_bytes = 0

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="ec-scrub"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)
        if self.cursor._dirty:
            self.cursor.save()

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.run_cycle()
            except Exception:  # noqa: BLE001 — scrubbing must never crash serving
                pass
            self._stop.wait(self.interval)

    # -- pacing --------------------------------------------------------------

    def _budget(self, n: int) -> None:
        """Admission + rate cap, applied before each chunk read. Admission
        first (a token refused means foreground traffic owns the lane —
        yield immediately, don't burn the rate window waiting); then the
        byte-rate cap over a rolling 1 s window."""
        while not self._stop.is_set():
            if self._admit is None or self._admit():
                break
            time.sleep(0.05)
        if self.rate_mb <= 0:
            return
        cap = self.rate_mb * 1024 * 1024
        now = time.monotonic()
        if now - self._window_t0 >= 1.0:
            self._window_t0, self._window_bytes = now, 0
        self._window_bytes += n
        over = self._window_bytes - cap * (now - self._window_t0)
        if over > 0:
            time.sleep(min(1.0, over / cap))

    # -- the scan ------------------------------------------------------------

    def _scan_order(self, vols: dict) -> Iterable[tuple[int, object]]:
        """Volumes in vid order, rotated so the cursor's vid comes first —
        a cycle interrupted by restart resumes where it stopped instead of
        re-paying the prefix volumes every time."""
        vids = sorted(vols)
        if self.cursor.vid in vols:
            i = vids.index(self.cursor.vid)
            vids = vids[i:] + vids[:i]
        for vid in vids:
            yield vid, vols[vid]

    def run_cycle(self) -> dict:
        """One pass over every mounted EC volume's local shards. Returns
        {"scanned_bytes", "shards_ok", "findings": [(vid, shard, verdict)],
        "unverifiable"} — the findings were already delivered to the
        callback one by one, as found (repair should not wait for the
        cycle to finish)."""
        with trace_mod.start("scrub.cycle", klass="scrub") as sp:
            out = self._run_cycle_inner()
            if sp is not None:
                sp.annotate(
                    scanned_bytes=out["scanned_bytes"],
                    shards_ok=out["shards_ok"],
                    findings=len(out["findings"]),
                )
            return out

    def _run_cycle_inner(self) -> dict:
        out = {
            "scanned_bytes": 0,
            "shards_ok": 0,
            "findings": [],
            "unverifiable": 0,
        }
        for vid, ev in self._scan_order(self._volumes()):
            if self._stop.is_set():
                break
            info = stripe.read_ec_info(ev.base)
            recorded = (info or {}).get("shard_crc32")
            if (
                not isinstance(recorded, list)
                or len(recorded) != stripe.geometry_from_info(info).total_shards
            ):
                # pre-CRC volume: nothing to verify against; counted so
                # operators can see coverage, not silently skipped
                out["unverifiable"] += 1
                continue
            want_size = expected_shard_size(info)
            # mid-cycle resume: the cursor names the first unfinished
            # shard of its volume (offset > 0 = resume mid-file with the
            # saved CRC accumulator; offset 0 = that shard from the top)
            resume_shard, resume_off, resume_crc = -1, 0, 0
            if vid == self.cursor.vid:
                resume_shard = self.cursor.shard
                resume_off, resume_crc = self.cursor.offset, self.cursor.crc
            for shard in sorted(ev.shard_ids):
                if self._stop.is_set():
                    break
                if shard in getattr(ev, "quarantined", {}):
                    continue  # already out of serving, repair owns it
                if shard < resume_shard:
                    continue  # scanned before the restart
                off = resume_off if shard == resume_shard else 0
                crc0 = resume_crc if shard == resume_shard else 0
                verdict = self._scan_one(
                    vid, ev, shard, want_size, recorded[shard], off, crc0
                )
                if verdict is None:
                    continue  # unmounted mid-scan (racing delete): skip
                if verdict == OK:
                    out["shards_ok"] += 1
                    out["scanned_bytes"] += want_size - off
                else:
                    out["findings"].append((vid, shard, verdict))
                    stats.ScrubCorruptionsFound.labels(verdict).inc()
                    try:
                        self._on_finding(vid, shard, verdict)
                    except Exception:  # noqa: BLE001 — policy failures must
                        pass  # not stop the scan of the remaining shards
        if self._stop.is_set():
            # interrupted cycle: _scan_one already persisted the exact
            # mid-shard resume point — resetting the cursor here would
            # clobber it and make the next generation rescan everything
            return out
        self.cursor.cycles += 1
        self.cursor.point(0, 0, 0, 0)
        self.cursor.save()
        stats.ScrubCycles.inc()
        return out

    def _scan_one(
        self,
        vid: int,
        ev,
        shard: int,
        want_size: int,
        want_crc: int,
        offset: int,
        crc: int,
    ) -> Optional[str]:
        """Scan one shard with periodic cursor persistence. None when the
        shard was unmounted while we were getting to it."""
        if shard not in ev._shard_files:
            return None
        path = stripe.shard_file_name(ev.base, shard)
        scanned = 0
        last_flush = 0
        state = {"crc": crc, "pos": offset}
        # chunked inline so the cursor can record mid-shard progress; the
        # plain scan_shard_file stays the simple reusable form (ec.verify)
        try:
            size = os.path.getsize(path)
        except OSError:
            return MISSING
        if size < want_size:
            return TRUNCATED
        if size > want_size:
            return CORRUPT
        try:
            with open(path, "rb") as f:
                f.seek(state["pos"])
                while state["pos"] < want_size:
                    if self._stop.is_set():
                        # persist exact progress; next cycle resumes here
                        self.cursor.point(vid, shard, state["pos"], state["crc"])
                        self.cursor.save()
                        return None
                    n = min(self.chunk_bytes, want_size - state["pos"])
                    self._budget(n)
                    chunk = f.read(n)
                    if len(chunk) != n:
                        return TRUNCATED
                    state["crc"] = zlib.crc32(chunk, state["crc"])
                    state["pos"] += n
                    scanned += n
                    stats.ScrubBytesScanned.inc(n)
                    if scanned - last_flush >= self._cursor_flush:
                        self.cursor.point(vid, shard, state["pos"], state["crc"])
                        self.cursor.save()
                        last_flush = scanned
        except OSError:
            return MISSING
        # shard complete: advance the cursor past it (offset 0 = the next
        # shard starts fresh); persisted so a restart resumes at the
        # shard boundary instead of re-paying this file
        self.cursor.point(vid, shard + 1, 0, 0)
        self.cursor.save()
        return OK if state["crc"] == (want_crc & 0xFFFFFFFF) else CORRUPT


def verify_ec_volume(
    ev,
    chunk_bytes: int = 4 * 1024 * 1024,
    budget: Optional[Callable[[int], None]] = None,
) -> tuple[dict[int, str], bool]:
    """Operator-facing full verification of one mounted EC volume's local
    shards -> ({shard: verdict}, has_crcs). The RPC/shell surface of the
    same math the background scrubber runs; quarantined shards report
    their quarantine reason without rescanning (the serving handle is
    gone — the verdict that put them there stands)."""
    info = stripe.read_ec_info(ev.base)
    recorded = (info or {}).get("shard_crc32")
    quarantined = dict(getattr(ev, "quarantined", {}) or {})
    if not isinstance(recorded, list) or len(recorded) != stripe.geometry_from_info(info).total_shards:
        verdicts = {s: UNVERIFIABLE for s in ev.shard_ids}
        verdicts.update({s: str(r) for s, r in quarantined.items()})
        return verdicts, False
    want_size = expected_shard_size(info)
    verdicts: dict[int, str] = {}
    for s, reason in quarantined.items():
        verdicts[s] = str(reason)
    for s in ev.shard_ids:
        verdicts[s] = scan_shard_file(
            stripe.shard_file_name(ev.base, s),
            recorded[s],
            want_size,
            chunk_bytes=chunk_bytes,
            budget=budget,
        )
    return verdicts, True
