"""Geometry conversion — re-encode an aging EC volume into a different
registered code family WITHOUT ever materializing the .dat or paying a
decode→re-encode round trip.

The GF-linear structure the repo already exploits (`Encoder.update_parity`,
`Encoder.project`) makes conversion a matrix applied to EXISTING shards:

  * data shards PASS THROUGH — a systematic code's data shards are ranges
    of the .dat laid out row-major, so the target geometry's data shards
    are a pure block REGROUP of the source's (identity coefficients; for
    k-preserving conversions the regroup is itself the identity and the
    source data files are reusable as-is);
  * new parity is a GF(2^8) PROJECTION of surviving shards — target parity
    row j = G_tgt[k_t+j] · data, and when a source data shard is missing
    the decode matrix folds in (`conversion_matrix` below), so the
    conversion never round-trips through a reconstructed .dat file.

Execution rides the EXACT streaming machinery the warm encoder uses: a
`_VirtualDat` file-shim maps dat-space reads onto source shard files
(reconstructing missing data shards from survivors inline), and
`stripe._encode_rows` runs its depth-N staging-ring pipeline over it —
flat (k_t, width) device dispatches, per-shard CRC32 folded in as bytes
stream out. Progress is journaled to a fsync'd `.ecc` sidecar (JSON
lines, torn tail ignored) so a SIGKILL mid-conversion resumes from the
last watermark instead of restarting; the staged target lives at
`<base>.cv.*` and the source geometry KEEPS SERVING until `cutover`
atomically retires it. Output is byte-exact vs the decode→re-encode
oracle (write_dat_file + write_ec_files on the target geometry) — the
tier-1 identity contract.

Bytes accounting (the BENCH_CONVERT gate): `bytes_written` = target
bytes the conversion materializes; the decode→re-encode oracle's cost is
its full I/O footprint (read data shards + write .dat + re-read .dat +
write the target set). Conversion must move <= 0.5x that.
"""

from __future__ import annotations

import json
import os
import time as _time
import zlib
from contextlib import ExitStack
from typing import Optional

import numpy as np

from seaweedfs_tpu.ec import locate
from seaweedfs_tpu.ec import stripe
from seaweedfs_tpu.obs import trace as trace_mod
from seaweedfs_tpu.ops import gf8
from seaweedfs_tpu.ops.rs_codec import (
    CodeGeometry,
    Encoder,
    geometry_for,
)
from seaweedfs_tpu.utils import config

JOURNAL_EXT = ".ecc"
#: staged-target base path suffix: the converted shard set is built at
#: `<base>.cv.ec00..` + `<base>.cv.eci` and only `cutover` moves it onto
#: the serving names — the old geometry serves reads the whole time.
STAGE_SUFFIX = ".cv"


class ConversionError(Exception):
    """Conversion could not run (bad source state, unknown family,
    un-resumable journal contradiction)."""


def stage_base(base: str) -> str:
    return base + STAGE_SUFFIX


def journal_path(base: str) -> str:
    return base + JOURNAL_EXT


# -- the conversion-matrix planner -------------------------------------------


def conversion_matrix(
    src: Encoder, tgt: Encoder, survivors: Optional[list] = None
) -> np.ndarray:
    """The (tgt_total x k) GF(2^8) matrix mapping `survivors` source shard
    columns to the FULL target shard set, for geometry pairs sharing a
    data-shard count: target rows = G_tgt · Dec where Dec inverts the
    source generator restricted to the survivor rows (identity when the
    survivors are exactly the data shards — data passes through, parity
    is a pure projection).

    For k-changing pairs (12+3, the 10+4 → 20+4 stripe merge) the SAME
    algebra applies per regrouped block column — data coefficients stay
    unit vectors over the regrouped blocks and parity rows are
    G_tgt[k_t:] — but there is no single whole-shard matrix because the
    block interleave period changes; the streaming converter IS that
    block-wise application (see `_VirtualDat`), so this planner raises
    rather than hand back a matrix that would mis-map columns."""
    if src.data_shards != tgt.data_shards:
        raise ConversionError(
            f"no whole-shard conversion matrix between k={src.data_shards} "
            f"and k={tgt.data_shards}: k-changing conversions apply the "
            "same coefficients per regrouped block (the streaming path)"
        )
    k = src.data_shards
    if survivors is None:
        survivors = list(range(k))
    survivors = [int(s) for s in survivors]
    if len(survivors) != k or len(set(survivors)) != k:
        raise ConversionError(
            f"need exactly {k} distinct survivor shard ids, got {survivors}"
        )
    sub = src.gen_matrix[survivors, :]  # (k, k)
    dec = gf8.gf_mat_inv(sub)  # survivors -> data
    out = gf8.gf_mat_mul(tgt.gen_matrix, dec).astype(np.uint8)
    out.setflags(write=False)
    return out


# -- virtual dat: the pass-through/projection read seam ----------------------


class _VirtualDat:
    """File-shim presenting the source shard set AS its .dat byte stream.

    `seek`/`readinto` are exactly what `stripe.read_padded_into` consumes,
    so the conversion pipeline is `stripe._encode_rows` UNCHANGED reading
    from here instead of a real .dat. Reads map dat offsets to source
    (shard, offset) runs via the source layout rule; bytes past `dat_size`
    are the layout's zero padding and never touch disk. A missing source
    data shard reconstructs per-run from the first k present shards
    (parity included) through the cached decode matrix — the ONLY GF
    decode work a conversion ever does, and only on degraded sources."""

    def __init__(self, base: str, info: dict, encoder: Encoder):
        self._base = base
        self._enc = encoder
        self.k = encoder.data_shards
        self.total = encoder.total_shards
        self.dat_size = int(info["dat_size"])
        self.large = int(info["large_block_size"])
        self.small = int(info["small_block_size"])
        self.bytes_read = 0
        self.reconstructed_bytes = 0
        self._pos = 0
        present = stripe.find_local_shards(base, self.total)
        missing_data = [d for d in range(self.k) if d not in present]
        if missing_data and len(present) < self.k:
            raise ConversionError(
                f"{base}: cannot read source data — {len(present)} shards "
                f"present, need {self.k} to reconstruct {missing_data}"
            )
        self._files = {}
        try:
            for s in present:
                # weedlint: ignore[open-no-ctx] handles owned by the shim, closed in close()
                self._files[s] = open(stripe.shard_file_name(base, s), "rb")
        except BaseException:
            self.close()
            raise
        self.missing_data = missing_data
        #: deterministic survivor pick for degraded reads: first k present
        self._survivors = present[: self.k]

    def close(self) -> None:
        for f in self._files.values():
            f.close()
        self._files.clear()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def seek(self, pos: int) -> None:
        self._pos = int(pos)

    def _map(self, pos: int) -> tuple[int, int, int]:
        """dat offset -> (source shard id, shard offset, contiguous run),
        through THE layout rule in locate.py (geometry-parameterized) —
        never a second inline copy of the block/row arithmetic."""
        block_index, is_large, n_large_rows, inner = locate.locate_offset(
            self.large, self.small, self.dat_size, pos, self.k
        )
        block_len = self.large if is_large else self.small
        sid, off = locate.Interval(
            block_index=block_index,
            inner_block_offset=inner,
            size=block_len - inner,
            is_large_block=is_large,
            large_block_rows_count=n_large_rows,
            data_shards=self.k,
        ).to_shard_id_and_offset(self.large, self.small)
        return sid, off, block_len - inner

    def _read_shard(self, sid: int, off: int, out: np.ndarray) -> None:
        f = self._files.get(sid)
        if f is not None:
            stripe.read_padded_into(f, off, out)
            self.bytes_read += out.size
            return
        # degraded source: decode this run from the survivor columns —
        # the conversion-matrix coefficients folded through the same
        # cached GF elimination every rebuild uses
        n = out.size
        shards: list[Optional[np.ndarray]] = [None] * self.total
        for s in self._survivors:
            buf = np.empty(n, dtype=np.uint8)
            stripe.read_padded_into(self._files[s], off, buf)
            shards[s] = buf
        rec = self._enc.reconstruct(shards, wanted=[sid])
        out[:] = rec[sid]
        self.bytes_read += n * len(self._survivors)
        self.reconstructed_bytes += n

    def readinto(self, mv) -> int:
        out = np.frombuffer(mv, dtype=np.uint8)
        n = out.size
        take = max(0, min(n, self.dat_size - self._pos))
        filled = 0
        while filled < take:
            sid, off, run = self._map(self._pos + filled)
            run = min(run, take - filled)
            self._read_shard(sid, off, out[filled : filled + run])
            filled += run
        self._pos += n
        return take  # short past dat EOF: caller zero-fills, like a file


# -- .ecc journal ------------------------------------------------------------


class _Journal:
    """Fsync'd JSON-lines conversion journal (the `.ecp` discipline):
    every record lands flush+fsync so an acked watermark survives a power
    cut; a torn tail record is ignored on read, costing at most one
    chunk's re-encode."""

    def __init__(self, path: str):
        self.path = path
        self._f = None

    def append(self, rec: dict) -> None:
        if self._f is None:
            # weedlint: ignore[open-no-ctx] journal handle owned for the conversion's life, closed in close()
            self._f = open(self.path, "ab")
        self._f.write(json.dumps(rec).encode() + b"\n")
        self._f.flush()
        os.fsync(self._f.fileno())

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None

    @staticmethod
    def read(path: str) -> list[dict]:
        return _Journal.read_prefix(path)[0]

    @staticmethod
    def read_prefix(path: str) -> tuple[list[dict], int]:
        """Records of the VALID journal prefix + its byte length. A torn
        tail (crash mid-append) is excluded — including a parseable final
        record with no terminating newline, which a later append would
        glue into garbage; dropping it costs at most one chunk's
        re-encode. Resume truncates the file to the returned length
        before reopening for append (the `.ecp` discipline)."""
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except OSError:
            return [], 0
        out: list[dict] = []
        pos = valid = 0
        for line in raw.split(b"\n"):
            end = pos + len(line) + 1  # + the newline split() removed
            if end > len(raw):
                break  # unterminated tail: never append after it
            if line.strip():
                try:
                    rec = json.loads(line)
                except ValueError:
                    break
                if isinstance(rec, dict):
                    out.append(rec)
            pos = valid = end
        return out, valid


def _begin_record(
    info: dict, src_geom: CodeGeometry, tgt_geom: CodeGeometry
) -> dict:
    """The journal header a resume validates against: a conversion may
    only continue over the EXACT source state it started from (the src
    .eci CRC list is the cheap whole-set fingerprint)."""
    return {
        "type": "begin",
        "src_family": src_geom.family,
        "tgt_family": tgt_geom.family,
        "src_total": src_geom.total_shards,
        "tgt_total": tgt_geom.total_shards,
        "dat_size": int(info["dat_size"]),
        "large_block_size": int(info["large_block_size"]),
        "small_block_size": int(info["small_block_size"]),
        "src_crc32": list(info.get("shard_crc32") or []),
    }


# -- the converter -----------------------------------------------------------


def _count_bytes(direction: str, n: int) -> None:
    if not n:
        return
    try:
        from seaweedfs_tpu import stats

        stats.EcConvertBytes.labels(direction).inc(n)
    except Exception:  # noqa: BLE001 — metrics must never break a conversion
        pass


def convert_ec_files(
    base_file_name: str,
    target_family: str,
    encoder: Optional[Encoder] = None,
    buffer_size: int = 1024 * 1024,
    max_batch_bytes: Optional[int] = None,
    journal_bytes: Optional[int] = None,
    pipeline_depth: Optional[int] = None,
    verify: Optional[bool] = None,
) -> dict:
    """Convert `<base>.ec*` from its recorded geometry to `target_family`,
    staging the result at `<base>.cv.ec*` + `<base>.cv.eci` (the source
    set keeps serving untouched). Crash-resumable via the `.ecc` journal;
    call `cutover` to atomically retire the old geometry afterwards.

    Returns accounting: {mode, src_family, target_family, bytes_read,
    bytes_written, reconstructed_bytes, shard_ids, seconds}."""
    t0 = _time.monotonic()
    jpath = journal_path(base_file_name)
    if pending_cutover(base_file_name):
        # a previous conversion COMPLETED and died mid-swap. This must be
        # decided BEFORE any geometry comparison: the swap renames `.eci`
        # first, so the live sidecar may already record the TARGET
        # geometry — the noop early-return below would strand the volume
        # un-mountable forever, and a different-family request would
        # mistake the journal for drift and discard the staged shards
        # (possibly the only complete copy). Finish the swap instead.
        out = finish_cutover(base_file_name)
        out["seconds"] = _time.monotonic() - t0
        return out
    info = stripe.read_ec_info(base_file_name)
    if info is None:
        raise ConversionError(
            f"{base_file_name}: no .eci sidecar — conversion needs the "
            "recorded dat size/geometry (re-encode legacy sets warm first)"
        )
    src_geom = stripe.geometry_from_info(info)
    tgt_geom = geometry_for(target_family)
    if (src_geom.data_shards, src_geom.parity_shards, src_geom.matrix_kind) == (
        tgt_geom.data_shards,
        tgt_geom.parity_shards,
        tgt_geom.matrix_kind,
    ):
        return {
            "mode": "noop",
            "src_family": src_geom.family,
            "target_family": tgt_geom.family,
            "bytes_read": 0,
            "bytes_written": 0,
            "reconstructed_bytes": 0,
            "shard_ids": list(range(tgt_geom.total_shards)),
            "seconds": 0.0,
        }
    enc_src = stripe.encoder_for_info(info, encoder)
    # same-backend target sibling: conversions ride whatever kernel/mesh
    # the factory measured fastest, exactly like encode/rebuild do
    tgt_info = {
        "data_shards": tgt_geom.data_shards,
        "parity_shards": tgt_geom.parity_shards,
        "matrix_kind": tgt_geom.matrix_kind,
        "family": tgt_geom.family,
    }
    enc_tgt = stripe.encoder_for_info(dict(info, **tgt_info), encoder)

    dat_size = int(info["dat_size"])
    large = int(info["large_block_size"])
    small = int(info["small_block_size"])
    k_t = tgt_geom.data_shards
    total_t = tgt_geom.total_shards
    n_large, n_small = stripe.stripe_layout(dat_size, large, small, k_t)
    shard_len = n_large * large + n_small * small
    staged = stage_base(base_file_name)
    batch = int(
        config.env("WEEDTPU_CONVERT_BATCH")
        if max_batch_bytes is None
        else max_batch_bytes
    )
    jbytes = int(
        config.env("WEEDTPU_CONVERT_JOURNAL_MB") * 1024 * 1024
        if journal_bytes is None
        else journal_bytes
    )
    do_verify = (
        bool(config.env("WEEDTPU_CONVERT_VERIFY")) if verify is None else verify
    )

    # -- resume decision ------------------------------------------------------
    begin = _begin_record(info, src_geom, tgt_geom)
    records, journal_valid_bytes = _Journal.read_prefix(jpath)
    resumed = False
    done_large = done_small = 0
    crcs = [0] * total_t
    carried_read = carried_written = carried_reconstructed = 0
    if records and records[0] == begin:
        # (a journaled cut-over intent was already handled at entry —
        # records here describe an in-flight, pre-cutover conversion)
        marks = [r for r in records if r.get("type") == "watermark"]
        if marks:
            m = marks[-1]
            sizes = [int(v) for v in m["sizes"]]
            ok = len(sizes) == total_t
            for s in range(total_t):
                p = stripe.shard_file_name(staged, s)
                if not ok:
                    break
                try:
                    if os.path.getsize(p) < sizes[s]:
                        ok = False  # file lost bytes the journal vouched for
                except OSError:
                    ok = False
            if ok:
                for s in range(total_t):
                    p = stripe.shard_file_name(staged, s)
                    with open(p, "r+b") as f:
                        f.truncate(sizes[s])
                done_large = int(m["rows_large"])
                done_small = int(m["rows_small"])
                crcs = [int(c) for c in m["crcs"]]
                carried_read = int(m.get("bytes_read", 0))
                carried_written = int(m.get("bytes_written", 0))
                carried_reconstructed = int(m.get("reconstructed", 0))
                resumed = True
    if not resumed:
        # fresh start: scrub any stale staged output + journal
        discard_staged(base_file_name, keep_journal=False)
        records = []
    else:
        # the crash that made this a resume may have left a torn tail
        # after the last valid record; _Journal.append reopens in 'ab',
        # so drop the fragment first or the next record glues onto it and
        # hides every later record (verified/cutover) from readers
        try:
            if os.path.getsize(jpath) > journal_valid_bytes:
                with open(jpath, "r+b") as jf:
                    jf.truncate(journal_valid_bytes)
        except OSError:
            pass

    journal = _Journal(jpath)
    written_since_mark = 0
    # one staging ring reused across every journal chunk of both row
    # tiers — without it each _encode_rows call reallocates the multi-
    # slot pinned ring (degenerate at small journal_bytes: one ring per
    # chunk)
    ring_cache: dict = {}
    try:
        if not resumed:
            journal.append(begin)

        with ExitStack() as stack:
            vdat = stack.enter_context(_VirtualDat(base_file_name, info, enc_src))
            outputs = [
                stack.enter_context(
                    open(stripe.shard_file_name(staged, s), "ab")
                )
                for s in range(total_t)
            ]

            def mark(rows_large: int, rows_small: int) -> None:
                # durability order: shard bytes reach disk BEFORE the
                # watermark vouches for them (fsync-then-record, the
                # inline-ingest discipline) — a crash can lose work, never
                # invent it
                for f in outputs:
                    f.flush()
                    os.fsync(f.fileno())
                journal.append(
                    {
                        "type": "watermark",
                        "rows_large": rows_large,
                        "rows_small": rows_small,
                        "sizes": [f.tell() for f in outputs],
                        "crcs": [int(c) for c in crcs],
                        "bytes_read": vdat.bytes_read + carried_read,
                        # f.tell() is the CUMULATIVE staged size (resume
                        # truncates then reopens append) — adding the
                        # carried count again would double-book pre-crash
                        # bytes in every post-resume watermark
                        "bytes_written": sum(f.tell() for f in outputs),
                        "reconstructed": vdat.reconstructed_bytes
                        + carried_reconstructed,
                    }
                )

            def run_phase(
                block: int,
                n_rows: int,
                done: int,
                region_start: int,
                is_large: bool,
            ) -> None:
                """Stream one row tier (large/small) through the staging-
                ring pipeline in journal-sized chunks of rows."""
                nonlocal written_since_mark
                row_bytes = (block * total_t) or 1
                rows_per_chunk = max(1, jbytes // row_bytes)
                row = done
                while row < n_rows:
                    n = min(rows_per_chunk, n_rows - row)
                    with trace_mod.span(
                        "convert.chunk",
                        tier="large" if is_large else "small",
                        row=row,
                        rows=n,
                    ):
                        stripe._encode_rows(
                            vdat,
                            enc_tgt,
                            outputs,
                            region_start + row * block * k_t,
                            block,
                            n,
                            min(buffer_size, block),
                            batch,
                            pipeline_depth,
                            crcs,
                            ring_cache=ring_cache,
                        )
                        row += n
                        written_since_mark += n * row_bytes
                        if written_since_mark >= jbytes or row >= n_rows:
                            mark(*((row, 0) if is_large else (n_large, row)))
                            written_since_mark = 0

            if done_small == 0:
                run_phase(large, n_large, done_large, 0, True)
            run_phase(
                small, n_small, done_small, n_large * large * k_t, False
            )

        bytes_written = total_t * shard_len
        # scrub-grade pre-cutover gate: what the NEW geometry will serve
        # is the bytes ON DISK — re-read them against the streamed CRCs
        # before the old geometry is retired
        if do_verify:
            try:
                for s in range(total_t):
                    p = stripe.shard_file_name(staged, s)
                    crc = 0
                    with open(p, "rb") as f:
                        if os.path.getsize(p) != shard_len:
                            raise ConversionError(
                                f"{p}: staged shard is {os.path.getsize(p)} "
                                f"bytes, layout wants {shard_len}"
                            )
                        while True:
                            chunk = f.read(1 << 20)
                            if not chunk:
                                break
                            crc = zlib.crc32(chunk, crc)
                    if crc != crcs[s]:
                        raise ConversionError(
                            f"{p}: on-disk CRC {crc} != streamed {crcs[s]} — "
                            "refusing cut-over over unvouched bytes"
                        )
            except ConversionError:
                # bad bytes BELOW the watermark (torn write, bit rot): a
                # journaled resume would trust the watermark, re-encode
                # nothing, and re-fail this verify on every re-issue —
                # scrub the staged state so the next attempt restarts
                # clean instead of wedging the volume unconvertible
                journal.close()
                discard_staged(base_file_name, keep_journal=False)
                raise
        stripe.write_ec_info(
            staged, large, small, dat_size, shard_crcs=crcs, geometry=tgt_geom
        )
        journal.append({"type": "verified" if do_verify else "staged"})
        total_read = vdat.bytes_read + carried_read
        total_reconstructed = vdat.reconstructed_bytes + carried_reconstructed
        # dispatch-seam counters book THIS RUN's delta only — a resume
        # after 99% must not re-book the pre-crash bytes the earlier run
        # already counted (the returned totals stay whole-conversion)
        _count_bytes("read", vdat.bytes_read)
        _count_bytes("written", max(0, bytes_written - carried_written))
        return {
            "mode": "resumed" if resumed else "converted",
            "src_family": src_geom.family,
            "target_family": tgt_geom.family,
            "bytes_read": total_read,
            "bytes_written": bytes_written,
            "reconstructed_bytes": total_reconstructed,
            "shard_ids": list(range(total_t)),
            "seconds": _time.monotonic() - t0,
        }
    finally:
        journal.close()


def discard_staged(base_file_name: str, keep_journal: bool = True) -> None:
    """Remove staged conversion output (and optionally the journal) —
    the fresh-start scrub and the operator abort path."""
    staged = stage_base(base_file_name)
    for s in range(stripe.MAX_SHARD_COUNT):
        try:
            os.unlink(stripe.shard_file_name(staged, s))
        except OSError:
            pass
    for ext in (".eci", ".eci.tmp"):
        try:
            os.unlink(staged + ext)
        except OSError:
            pass
    if not keep_journal:
        try:
            os.unlink(journal_path(base_file_name))
        except OSError:
            pass


def _journal_state(base_file_name: str) -> list[dict]:
    return _Journal.read(journal_path(base_file_name))


def pending_cutover(base_file_name: str) -> bool:
    """True while a journaled cut-over intent is UNFINISHED — the window
    between `cutover`'s intent record and `finish_cutover`'s final journal
    unlink, where `.eci` and the shard files may describe different
    geometries. A mount in this window must refuse (EcVolume consults
    this) and `convert_ec_files` resumes by finishing the swap."""
    return any(
        r.get("type") == "cutover" for r in _journal_state(base_file_name)
    )


def cutover(base_file_name: str) -> dict:
    """Atomically retire the source geometry: verify the staged set is
    complete, journal the cut-over intent, then swap `.eci` FIRST (the
    single source of truth — a crash mid-swap leaves a volume that
    REFUSES to mount with typed EcGeometryError rather than one that
    silently misreads) and the shard files after, dropping stale
    source-only shard ids. Idempotent: `finish_cutover` completes a
    crashed swap from the journal."""
    records = _journal_state(base_file_name)
    if not records or records[0].get("type") != "begin":
        raise ConversionError(
            f"{base_file_name}: no conversion journal — nothing to cut over"
        )
    if not any(r.get("type") in ("verified", "staged") for r in records):
        raise ConversionError(
            f"{base_file_name}: conversion has not completed verification"
        )
    staged = stage_base(base_file_name)
    begin = records[0]
    total_t = int(begin["tgt_total"])
    for s in range(total_t):
        if not os.path.exists(stripe.shard_file_name(staged, s)):
            raise ConversionError(
                f"{base_file_name}: staged shard {s} missing — cannot cut over"
            )
    if not os.path.exists(staged + ".eci"):
        raise ConversionError(
            f"{base_file_name}: staged .eci missing — cannot cut over"
        )
    j = _Journal(journal_path(base_file_name))
    try:
        j.append({"type": "cutover"})
    finally:
        j.close()
    return finish_cutover(base_file_name)


def finish_cutover(base_file_name: str) -> dict:
    """Complete (or re-complete after a crash) the file swap the journal's
    `cutover` record promised. Every step is idempotent: replace staged
    files that still exist, keep already-swapped ones, drop stale
    source-only shards, then drop the journal LAST (its presence is what
    makes a half-swapped volume recoverable)."""
    records = _journal_state(base_file_name)
    begin = records[0] if records else None
    if begin is None or not any(r.get("type") == "cutover" for r in records):
        raise ConversionError(
            f"{base_file_name}: journal carries no cut-over intent"
        )
    staged = stage_base(base_file_name)
    total_t = int(begin["tgt_total"])
    src_total = int(begin.get("src_total") or 0)
    # .eci first: the sidecar IS the geometry truth — after this rename
    # the volume is a target-geometry volume whose shard files are being
    # filled in (a mount in the gap refuses loudly, never misreads)
    if os.path.exists(staged + ".eci"):
        os.replace(staged + ".eci", base_file_name + ".eci")
    for s in range(total_t):
        sp = stripe.shard_file_name(staged, s)
        if os.path.exists(sp):
            os.replace(sp, stripe.shard_file_name(base_file_name, s))
        elif not os.path.exists(stripe.shard_file_name(base_file_name, s)):
            raise ConversionError(
                f"{base_file_name}: shard {s} lost mid-cutover (neither "
                "staged nor live file exists)"
            )
    for s in range(total_t, max(src_total, total_t)):
        try:
            os.unlink(stripe.shard_file_name(base_file_name, s))
        except OSError:
            pass
    try:
        os.unlink(journal_path(base_file_name))
    except OSError:
        pass
    return {
        "mode": "cutover",
        "src_family": str(begin.get("src_family", "")),
        "target_family": str(begin.get("tgt_family", "")),
        "bytes_read": 0,
        "bytes_written": 0,
        "reconstructed_bytes": 0,
        "shard_ids": list(range(total_t)),
    }


def reencode_oracle_bytes(base_file_name: str, target_family: str) -> dict:
    """The decode→re-encode round trip's deterministic I/O footprint for
    this volume — the denominator of the conversion gate, computed from
    the recorded geometry (no oracle run needed): read the source data
    shards (= dat bytes), write the .dat, re-read it, write the full
    target shard set. BASELINE.md 'Conversion methodology' states the
    formula; the bench ALSO runs the real oracle and asserts the
    measured sizes match this accounting."""
    info = stripe.read_ec_info(base_file_name)
    if info is None:
        raise ConversionError(f"{base_file_name}: no .eci sidecar")
    tgt = geometry_for(target_family)
    dat = int(info["dat_size"])
    large = int(info["large_block_size"])
    small = int(info["small_block_size"])
    n_large, n_small = stripe.stripe_layout(dat, large, small, tgt.data_shards)
    tgt_bytes = tgt.total_shards * (n_large * large + n_small * small)
    return {
        "decode_read": dat,
        "decode_written": dat,
        "encode_read": dat,
        "encode_written": tgt_bytes,
        "total": 3 * dat + tgt_bytes,
    }
