"""Inline-ingest parity spreading — stream parity rows to their
placement-planned eventual holders WHILE the volume is still taking
writes (the PR 8 residual).

Without spreading, an inline-sealed volume is born with ALL k+m shards
on its owner: cut-over to a spread layout is a later bulk copy, and
until then one node failure risks the whole stripe. With
WEEDTPU_INLINE_EC_SPREAD=on the owner tees each parity shard's encoded
rows to a target chosen by the failure-domain planner
(`placement.plan_parity_targets`) as the rows land in the local
partials: `VolumeEcShardPartialWrite` appends into the target's
`.ecNN.inp` (invisible to shard discovery), and at seal time
`VolumeEcShardSpreadCommit` truncates, CRC-verifies against the .eci
record, renames the partial into a real shard, pulls the index files
from the owner, and mounts — so the cut-over ships only the small tail
and the owner never hosts all k+m.

Spreading is STRICTLY an optimization: every parity byte also lands in
the owner's local partial exactly as before, any ship/commit failure
marks that shard's spread broken and the seal keeps the local copy, and
delta parity patches below the shipped watermark simply mark the range
dirty for an idempotent absolute-offset re-ship. Zero new failure modes
on the ingest path.
"""

from __future__ import annotations

import os
import threading
import zlib
from typing import Optional

from seaweedfs_tpu import stats
from seaweedfs_tpu.obs import trace as trace_mod
from seaweedfs_tpu.pb import VOLUME_SERVICE

#: one partial-write RPC's payload bound (b64-inflated on the JSON wire)
SHIP_CHUNK = 1024 * 1024
#: per-RPC deadline — a slow target breaks the spread (local fallback),
#: never stalls the encoder worker behind a wedged peer
SHIP_TIMEOUT = 10.0


class ShardSpreadState:
    __slots__ = ("shard_id", "addr", "shipped", "dirty", "broken", "committed")

    def __init__(self, shard_id: int, addr: str):
        self.shard_id = shard_id
        self.addr = addr  # target grpc host:port
        self.shipped = 0  # bytes [0, shipped) already at the target
        self.dirty: list[tuple[int, int]] = []  # delta-patched ranges to re-ship
        self.broken = False
        self.committed = False


class SpreadSession:
    """One ingesting volume's parity tee. Methods are called from the
    ingest encoder worker (poll) and the seal path; a lock serializes
    them against the delta-patch notifications arriving from the
    builder's overwrite path."""

    def __init__(
        self,
        vid: int,
        collection: str,
        base: str,
        targets: dict[int, str],
        pool,
        data_shards: int,
        large_block: int,
    ):
        self.vid = vid
        self.collection = collection
        self.base = base
        self.pool = pool  # rpc.ClientPool (shared with the server's peers)
        self.data_shards = int(data_shards)
        self.large = int(large_block)
        self._lock = threading.Lock()
        self.shards: dict[int, ShardSpreadState] = {
            sid: ShardSpreadState(sid, addr) for sid, addr in targets.items()
        }

    # -- builder hooks -------------------------------------------------------

    def note_patch(self, shard_id: int, pos: int, length: int) -> None:
        """A delta parity update rewrote [pos, pos+length) of a parity
        partial. The range is ALWAYS marked dirty — a concurrent poll()
        may already have read the pre-patch bytes for an offset past
        `shipped` without having advanced the watermark yet, so gating
        on `pos < shipped` would drop exactly those patches. Re-shipping
        an unshipped (or twice-shipped) range is an idempotent
        absolute-offset write; deltas are rare, the redundancy is
        cheap."""
        with self._lock:
            st = self.shards.get(shard_id)
            if st is None or st.broken:
                return
            st.dirty.append((pos, length))

    def poll(self, encoded_rows: int) -> None:
        """Ship each parity shard's new rows [shipped, encoded_rows*large)
        plus any dirty ranges, reading from the owner's local partial.
        Failures mark just that shard broken — the seal keeps its local
        copy and the other targets keep receiving."""
        limit = int(encoded_rows) * self.large
        for st in list(self.shards.values()):
            if st.broken or st.committed:
                continue
            with self._lock:
                dirty, st.dirty = st.dirty, []
                start = st.shipped
            try:
                from seaweedfs_tpu.ec import ingest as ingest_mod

                path = ingest_mod.part_path(self.base, st.shard_id)
                if not os.path.exists(path):
                    # the seal just renamed the partial into its final
                    # shard: finalize() owns the tail from here — NOT a
                    # failure (marking broken here would undo the whole
                    # spread in the poll/seal race window)
                    continue
                with open(path, "rb") as f:
                    for off, length in dirty:
                        f.seek(off)
                        self._ship(st, off, f.read(length))
                    pos = start
                    while pos < limit:
                        f.seek(pos)
                        chunk = f.read(min(SHIP_CHUNK, limit - pos))
                        if not chunk:
                            break
                        self._ship(st, pos, chunk)
                        pos += len(chunk)
                with self._lock:
                    st.shipped = max(st.shipped, pos)
            except Exception:  # noqa: BLE001 — spread is best-effort
                st.broken = True

    def _ship(self, st: ShardSpreadState, offset: int, data: bytes) -> None:
        import base64 as _b64

        self.pool.get(st.addr).call(
            VOLUME_SERVICE,
            "VolumeEcShardPartialWrite",
            {
                "volume_id": self.vid,
                "collection": self.collection,
                "shard_id": st.shard_id,
                "offset": int(offset),
                "data": _b64.b64encode(data).decode(),
            },
            timeout=SHIP_TIMEOUT,
        )
        stats.InlineEcSpreadBytes.inc(len(data))

    # -- seal ----------------------------------------------------------------

    def finalize(
        self, source_grpc: str, shard_crcs, shard_size: int
    ) -> list[int]:
        """Seal cut-over: ship each unbroken target its tail (reading the
        FINAL shard files — the partials were just renamed into place),
        then commit (truncate to size, CRC-verify vs .eci, rename, pull
        index files, mount). Returns the parity shard ids now hosted
        remotely; the caller unlinks/unmounts those locally. Any failure
        leaves that shard local — never both-or-neither."""
        from seaweedfs_tpu.ec import stripe

        done: list[int] = []
        for st in list(self.shards.values()):
            if st.broken:
                continue
            try:
                with trace_mod.span("ingest.spread.commit", shard=st.shard_id):
                    with open(
                        stripe.shard_file_name(self.base, st.shard_id), "rb"
                    ) as f:
                        with self._lock:
                            dirty, st.dirty = st.dirty, []
                            pos = st.shipped
                        for off, length in dirty:
                            f.seek(off)
                            self._ship(st, off, f.read(length))
                        while pos < shard_size:
                            f.seek(pos)
                            chunk = f.read(min(SHIP_CHUNK, shard_size - pos))
                            if not chunk:
                                break
                            self._ship(st, pos, chunk)
                            pos += len(chunk)
                    resp = self.pool.get(st.addr).call(
                        VOLUME_SERVICE,
                        "VolumeEcShardSpreadCommit",
                        {
                            "volume_id": self.vid,
                            "collection": self.collection,
                            "shard_id": st.shard_id,
                            "size": int(shard_size),
                            "crc32": int(shard_crcs[st.shard_id]) & 0xFFFFFFFF,
                            "source_data_node": source_grpc,
                            "mount": True,
                        },
                        timeout=60,
                    )
                if resp.get("mounted"):
                    st.committed = True
                    stats.InlineEcSpreadCommits.labels("ok").inc()
                    done.append(st.shard_id)
                else:
                    st.broken = True
                    stats.InlineEcSpreadCommits.labels("failed").inc()
            except Exception:  # noqa: BLE001 — keep the shard local
                st.broken = True
                stats.InlineEcSpreadCommits.labels("failed").inc()
        return done

    def abort(self) -> None:
        """Discard remote partials (size=0 commit = delete the .inp) —
        called when the builder aborts or a warm/shell seal supersedes
        the spread."""
        for st in list(self.shards.values()):
            if st.committed:
                continue
            try:
                self.pool.get(st.addr).call(
                    VOLUME_SERVICE,
                    "VolumeEcShardSpreadCommit",
                    {
                        "volume_id": self.vid,
                        "collection": self.collection,
                        "shard_id": st.shard_id,
                        "size": 0,  # contract: 0 = discard the partial
                        "crc32": 0,
                        "source_data_node": "",
                        "mount": False,
                    },
                    timeout=SHIP_TIMEOUT,
                )
            except Exception:  # noqa: BLE001 — orphan .inp on a dead peer
                pass  # is invisible to discovery and tiny; best-effort


def local_crc(path: str) -> int:
    crc = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                break
            crc = zlib.crc32(chunk, crc)
    return crc
