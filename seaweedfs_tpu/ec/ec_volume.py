"""EcVolume — the serving-side view of one EC volume's shard set.

Mirror of weed/storage/erasure_coding/ec_volume.go + the read path of
weed/storage/store_ec.go (ReadEcShardNeedle / readEcShardIntervals /
recoverOneRemoteEcShardInterval) [VERIFY: mount empty; SURVEY.md §3.2].

Needle lookup: binary search of the sorted .ecx (vectorized: the index is
loaded once into a numpy structured array and searched with searchsorted).
Interval reads hit local shard files; a missing shard falls back to the
injected remote reader, then to reconstruction from >=10 surviving shards —
the degraded-read path whose p50 latency is a north-star metric.
"""

from __future__ import annotations

import os
import threading
import time as _time
import zlib
from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor, wait
from concurrent.futures import TimeoutError as _FutureTimeout  # 3.10: not builtins.TimeoutError
from typing import Callable, Optional

import numpy as np

from seaweedfs_tpu import stats
from seaweedfs_tpu.obs import trace as trace_mod

from seaweedfs_tpu.ec import locate as locate_mod
from seaweedfs_tpu.ec import stripe
from seaweedfs_tpu.ec import suspicion as suspicion_mod
from seaweedfs_tpu.ec.constants import (
    ERASURE_CODING_LARGE_BLOCK_SIZE,
    ERASURE_CODING_SMALL_BLOCK_SIZE,
)
from seaweedfs_tpu.ops.rs_codec import Encoder, new_encoder
from seaweedfs_tpu.storage import idx as idx_mod
from seaweedfs_tpu.storage import types
from seaweedfs_tpu.utils import config

# remote_reader(shard_id, offset, size) -> bytes | None
RemoteReader = Callable[[int, int, int], Optional[bytes]]


class NeedleNotFound(KeyError):
    pass


class NeedleDeleted(Exception):
    pass


class EcGeometryError(ValueError):
    """The on-disk shard set contradicts the .eci-recorded geometry —
    shard ids past the recorded total, or a shard file longer than the
    layout allows. Mounting anyway would silently mis-map every interval
    (before geometry validation, a wrong-geometry shard set was only
    caught by CRC luck on the first degraded read). Typed so the volume
    server can refuse the mount loudly and discovery can skip the volume
    instead of serving garbage."""

    def __init__(self, msg: str, base: str = "", details: Optional[dict] = None):
        super().__init__(msg)
        self.base = base
        #: machine-readable mismatch description (shard ids / sizes)
        self.details = dict(details or {})


class EcDegradedReadError(IOError):
    """A degraded read could not be served. Typed (instead of a bare
    IOError/None bubble) so the volume server can answer 503 with a
    Retry-After hint and operators can count failure classes apart.
    Carries WHO was attempted and what the suspicion registry thought at
    failure time — the difference between "the cluster lost the stripe"
    and "one wedged peer is poisoning the ladder"."""

    #: seconds a client should back off before retrying; subclasses pick
    #: a default matched to their failure mode, callers may override
    retry_after: float = 1.0

    def __init__(
        self,
        msg: str,
        shard_id: Optional[int] = None,
        attempted: tuple = (),
        suspected: tuple = (),
        retry_after: Optional[float] = None,
    ):
        super().__init__(msg)
        self.shard_id = shard_id
        #: holder keys (peer addrs when the reader names peers, else
        #: (volume, shard) tuples) the read actually tried
        self.attempted = list(attempted)
        #: holder keys sitting in a suspicion window when the read failed
        self.suspected = list(suspected)
        if retry_after is not None:
            self.retry_after = retry_after


class EcNoViableHolders(EcDegradedReadError):
    """Too few survivors reachable and no attempt still pending: every
    candidate answered a miss, erred, or sat suspected. Retrying sooner
    than the suspicion backoff mostly re-fails, hence the longer hint."""

    retry_after = 5.0


class EcDegradedReadTimeout(EcDegradedReadError):
    """The overall recover deadline expired with fetches still in flight —
    survivors exist but answered too slowly; a prompt retry may win."""

    retry_after = 1.0


class EcShardCorrupt(EcDegradedReadError):
    """The read failed AND this volume has shards quarantined for failed
    integrity verification — no clean copy could serve the interval. The
    scrubber's auto-repair is (or will be) rebuilding the quarantined
    shards, so the retry hint matches the repair timescale, and the
    operator-facing class says 'corruption', not 'holders down'."""

    retry_after = 5.0

    def __init__(self, msg: str, quarantined: Optional[dict] = None, **kw):
        super().__init__(msg, **kw)
        #: {shard_id: reason} snapshot of the volume's quarantine registry
        self.quarantined = dict(quarantined or {})


class _CoalesceSlot:
    """One in-flight degraded decode: the leader publishes its result (or
    error) here and sets the event; waiters read it instead of decoding."""

    __slots__ = ("event", "result", "error")

    def __init__(self) -> None:
        self.event = threading.Event()
        self.result: Optional[np.ndarray] = None
        self.error: Optional[BaseException] = None


class EcVolume:
    def __init__(
        self,
        base_file_name: str,
        encoder: Optional[Encoder] = None,
        large_block_size: int = ERASURE_CODING_LARGE_BLOCK_SIZE,
        small_block_size: int = ERASURE_CODING_SMALL_BLOCK_SIZE,
        remote_reader: Optional[RemoteReader] = None,
        version: int = 3,
        shard_size: Optional[int] = None,
        warm_on_mount: bool = True,
        ecj_compact_threshold: int = 1 << 20,
        recover_fetch_parallelism: int = 8,
        recover_fetch_deadline: float = 30.0,
        recover_holder_timeout: float = 30.0,
        recover_holder_backoff: float = 30.0,
        recover_suspect_after: float = 5.0,
        suspicion: Optional[suspicion_mod.HolderSuspicion] = None,
    ):
        self.base = base_file_name
        self.encoder = encoder or new_encoder()
        self.remote_reader = remote_reader
        self.version = version
        # degraded-read survivor fan-out (lazily built: most volumes never
        # take a reconstructing read, and a pool per mount would leak threads)
        self.recover_fetch_parallelism = recover_fetch_parallelism
        self.recover_fetch_deadline = recover_fetch_deadline
        # per-HOLDER cap + suspicion window: a WEDGED holder (SIGSTOPped
        # process, dead NIC — it neither answers nor errors) is cut at
        # `recover_holder_timeout` per attempt, then skipped entirely for
        # `recover_holder_backoff` seconds, so one wedged peer costs the
        # ladder ONE capped attempt — not a per-read stall — and the
        # serving p50 returns to healthy levels until the window expires.
        # The cap default (30 s) deliberately exceeds the volume server's
        # remote_reader internals (per-holder 10 s transport timeout x a
        # couple of replica holders): a reader mid-failover to a healthy
        # replica must never be aborted and suspected by this layer. The
        # cap's hard cut matters for readers WITHOUT internal timeouts.
        # `recover_suspect_after` is the complementary soft signal: a
        # remote fetch that runs at least this long and still yields
        # NOTHING (the shape of a reader whose internal timeout swallowed
        # a wedged peer) marks the shard suspect — a genuine miss (shard
        # simply absent) answers None fast and is never suspected.
        self.recover_holder_timeout = recover_holder_timeout
        self.recover_holder_backoff = recover_holder_backoff
        self.recover_suspect_after = recover_suspect_after
        # suspicion state lives in a PROCESS-WIDE registry keyed by peer
        # identity when the reader can name peers (see _holder_key): a
        # wedged peer serving many volumes costs one capped attempt
        # process-wide, not one per volume
        self._suspicion = suspicion if suspicion is not None else suspicion_mod.GLOBAL
        self._fetch_pool: Optional[ThreadPoolExecutor] = None
        self._fetch_pool_lock = threading.Lock()
        # single-flight coalescing of concurrent degraded decodes of the
        # SAME (shard, offset, size): key -> _CoalesceSlot. The lock is
        # leaf-level (never held across another acquisition or any I/O).
        self._coalesce: dict[tuple[int, int, int], "_CoalesceSlot"] = {}
        self._coalesce_lock = threading.Lock()
        # recorded stripe geometry (.eci) wins over constructor defaults —
        # opening shards with the wrong geometry would mis-map every interval
        info = stripe.read_ec_info(base_file_name)
        if info is not None:
            self.large = int(info["large_block_size"])
            self.small = int(info["small_block_size"])
        else:
            self.large = large_block_size
            self.small = small_block_size
        # code geometry: recorded in the .eci for geometry-flexible volumes
        # (ec.convert targets), implied legacy 10+4 otherwise. The serving
        # encoder must MATCH it — a caller-supplied encoder of a different
        # geometry is replaced by a same-backend sibling, never trusted to
        # decode a layout it does not describe.
        self.geometry = stripe.geometry_from_info(info)
        self.data_shards = self.geometry.data_shards
        self.total_shards = self.geometry.total_shards
        self.encoder = stripe.encoder_for_info(info, self.encoder)

        # mount-time journal compaction: a delete-heavy volume's .ecj is
        # folded into .ecx tombstones once it crosses the threshold, so the
        # journal (and its replay cost) stays bounded over the volume's life
        ecj_path = base_file_name + ".ecj"
        if (
            ecj_compact_threshold
            and os.path.exists(ecj_path)
            and os.path.getsize(ecj_path) >= ecj_compact_threshold
        ):
            stripe.compact_ecj(base_file_name)

        with open(base_file_name + ".ecx", "rb") as f:
            self._index = idx_mod.index_entries_array(f.read())
        self._keys = self._index["key"]
        self._deleted = set(stripe.read_ecj(base_file_name))

        self._shard_files = {}
        # shards pulled out of serving by failed integrity verification:
        # {shard_id: reason} ("corrupt" | "truncated" | "missing"). The
        # serving handle is closed (reads route local -> remote ->
        # reconstruct around it) and VolumeStatus surfaces the entry so
        # rebuilding peers and operators see WHY the shard is gone.
        self.quarantined: dict[int, str] = {}
        self.shard_size = shard_size or 0
        try:
            self._validate_geometry(info)
            for s in range(self.total_shards):
                p = stripe.shard_file_name(base_file_name, s)
                if os.path.exists(p):
                    # weedlint: ignore[open-no-ctx] serving handles owned by the volume, closed in close()
                    self._shard_files[s] = open(p, "rb")
                    self.shard_size = max(self.shard_size, os.path.getsize(p))
        except BaseException:
            for f in self._shard_files.values():
                f.close()
            self._shard_files.clear()
            raise
        if self.shard_size == 0 and remote_reader is not None and len(self._index):
            # No local shard to size the volume from: large-vs-small row math
            # would silently mis-map offsets, so demand an explicit size.
            raise ValueError(
                "EcVolume with no local shards needs an explicit shard_size "
                "to locate blocks correctly"
            )
        # The locate math only needs the large-row count; shard_size * D is a
        # consistent stand-in for the true .dat size (ev.DatFileSize analog);
        # the recorded exact size wins when available.
        if info is not None:
            self.dat_file_size = int(info["dat_size"])
        else:
            self.dat_file_size = self.shard_size * self.data_shards

        # resident hot path (SURVEY §7.3.5): pre-build the serving-path
        # decode matrices and pre-compile the bucketed reconstruct shapes in
        # the background so the first degraded client read is warm; join
        # `warm_thread` to wait for it (tests/bench)
        self.warm_thread: Optional[threading.Thread] = None
        if warm_on_mount:
            self.warm_thread = threading.Thread(target=self._warm, daemon=True)
            self.warm_thread.start()

    def _validate_geometry(self, info: Optional[dict]) -> None:
        """Mount-time shard-count/geometry consistency gate: the local
        shard set must FIT the .eci-recorded (or legacy-implied) geometry.
        Stray shard ids past the recorded total, or a shard file longer
        than the recorded layout allows, mean the files and the sidecar
        describe different codes — reading on would silently mis-map
        intervals (previously only caught by CRC luck), so the mount
        raises typed EcGeometryError instead."""
        # a journaled-but-unfinished conversion cut-over means `.eci` and
        # the shard files may describe DIFFERENT geometries (the .eci
        # swaps first; the journal is unlinked last) — and when the two
        # layouts' shard sizes coincide, neither the stray-id nor the
        # over-length check below can tell. Refuse until the convert
        # resume path finishes the swap.
        from seaweedfs_tpu.ec import convert as convert_mod

        if convert_mod.pending_cutover(self.base):
            raise EcGeometryError(
                f"{self.base}: conversion cut-over in progress (journaled "
                "intent, swap unfinished) — resume `ec.convert` to finish "
                "the swap before mounting",
                base=self.base,
                details={"pending_cutover": True},
            )
        stray = [
            s
            for s in stripe.find_local_shards(self.base)
            if s >= self.total_shards
        ]
        if stray:
            raise EcGeometryError(
                f"{self.base}: shard files {stray} exceed the recorded "
                f"{self.geometry.family} geometry "
                f"({self.data_shards}+{self.geometry.parity_shards}) — "
                "wrong-geometry shard set?",
                base=self.base,
                details={"stray_shards": stray, "family": self.geometry.family},
            )
        if info is None:
            return  # legacy sidecar-less set: sizes are unvouchable
        n_large, n_small = stripe.stripe_layout(
            int(info["dat_size"]), self.large, self.small, self.data_shards
        )
        expected = n_large * self.large + n_small * self.small
        over = {
            s: os.path.getsize(stripe.shard_file_name(self.base, s))
            for s in stripe.find_local_shards(self.base, self.total_shards)
            if os.path.getsize(stripe.shard_file_name(self.base, s)) > expected
        }
        if over:
            # over-length is a GEOMETRY contradiction (a truncated shard is
            # bit-rot/crash damage and stays the scrub ladder's business)
            raise EcGeometryError(
                f"{self.base}: shard files longer than the recorded layout "
                f"allows ({over} > {expected} bytes for "
                f"{self.geometry.family}) — wrong-geometry shard set?",
                base=self.base,
                details={"over_length": over, "expected_size": expected},
            )

    def _warm(self) -> None:
        try:
            self.encoder.warm_decode_matrices(local_shards=self.shard_ids)
            self.encoder.warm_reconstruct()
        except Exception:  # noqa: BLE001 — warmup must never break a mount
            pass

    def close(self) -> None:
        for f in self._shard_files.values():
            f.close()
        self._shard_files.clear()
        # unmount forgets this volume's (volume, shard)-scoped suspicion —
        # a remount must not inherit stale windows (peer-scoped windows
        # persist: they describe the peer, not this volume)
        self._suspicion.forget_volume(self.base)
        with self._fetch_pool_lock:
            pool, self._fetch_pool = self._fetch_pool, None
        if pool is not None:
            pool.shutdown(wait=False, cancel_futures=True)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    @property
    def shard_ids(self) -> list[int]:
        return sorted(self._shard_files)

    def verify_local_shards(self) -> Optional[dict]:
        """Check every locally-held shard file against the CRC32s the
        streaming encode recorded in the .eci sidecar (and rebuilds verify
        on write) — the fsck-style integrity pass for a mounted EC volume.
        Returns {shard_id: ok} or None when the volume predates CRC
        recording (no shard_crc32 in the sidecar)."""
        info = stripe.read_ec_info(self.base)
        recorded = (info or {}).get("shard_crc32")
        if not isinstance(recorded, list) or len(recorded) != self.total_shards:
            return None
        out = {}
        for s in sorted(self._shard_files):
            # private handle per shard: the serving handles in
            # self._shard_files are seek/read'd by concurrent interval
            # reads, and an fsck pass sharing them would race both sides
            with open(stripe.shard_file_name(self.base, s), "rb") as f:
                crc = 0
                while True:
                    chunk = f.read(1 << 20)
                    if not chunk:
                        break
                    crc = zlib.crc32(chunk, crc)
            out[s] = crc == recorded[s]
        return out

    def drop_local_shard(self, shard_id: int) -> bool:
        """Stop serving a shard from local disk (single-shard unmount /
        shard-file loss): closes the handle so reads fall through to the
        remote -> reconstruct ladder."""
        f = self._shard_files.pop(shard_id, None)
        if f is None:
            return False
        f.close()
        return True

    def quarantine_shard(self, shard_id: int, reason: str = "corrupt") -> bool:
        """Pull a shard that failed integrity verification out of serving:
        the handle closes (degraded reads route around it instead of
        decoding garbage into a client response) and the reason is
        remembered for VolumeStatus / the typed EcShardCorrupt error.
        Returns whether a serving handle was actually dropped."""
        self.quarantined[shard_id] = str(reason)
        return self.drop_local_shard(shard_id)

    def mount_local_shard(self, shard_id: int) -> bool:
        """(Re)open one shard file for serving — the repair path's remount
        after a quarantined shard was rebuilt and re-verified. Clears the
        quarantine entry. False when the file does not exist."""
        p = stripe.shard_file_name(self.base, shard_id)
        try:
            # weedlint: ignore[open-no-ctx] serving handle owned by the volume, closed in close()
            f = open(p, "rb")
        except OSError:
            return False
        old = self._shard_files.pop(shard_id, None)
        if old is not None:
            old.close()
        self._shard_files[shard_id] = f
        self.shard_size = max(self.shard_size, os.path.getsize(p))
        self.quarantined.pop(shard_id, None)
        return True

    # -- index ---------------------------------------------------------------

    def find_needle_from_ecx(self, needle_id: int) -> tuple[int, int]:
        """-> (actual_byte_offset, size). Raises NeedleNotFound/NeedleDeleted."""
        pos = int(np.searchsorted(self._keys, np.uint64(needle_id)))
        if pos >= len(self._keys) or int(self._keys[pos]) != needle_id:
            raise NeedleNotFound(needle_id)
        entry = self._index[pos]
        size = int(entry["size"])
        if types.is_deleted(size) or needle_id in self._deleted:
            raise NeedleDeleted(needle_id)
        return types.offset_to_actual(int(entry["offset"])), size

    def locate_needle(self, needle_id: int) -> tuple[int, int, list[locate_mod.Interval]]:
        """LocateEcShardNeedle: -> (offset, size, intervals covering the full
        on-disk record: header + body + checksum [+ts] + padding)."""
        offset, size = self.find_needle_from_ecx(needle_id)
        whole = types.actual_size(size, self.version)
        intervals = locate_mod.locate_data(
            self.large, self.small, self.dat_file_size, offset, whole,
            self.data_shards,
        )
        return offset, size, intervals

    # -- interval reads ------------------------------------------------------

    def _read_local(self, shard_id: int, offset: int, size: int) -> Optional[np.ndarray]:
        f = self._shard_files.get(shard_id)
        if f is None:
            return None
        try:
            f.seek(offset)
            raw = f.read(size)
        except (ValueError, OSError):
            # handle closed underneath us (concurrent quarantine/unmount)
            # or the disk faulted mid-read: both mean "this local copy is
            # unavailable", and the remote/reconstruct ladder owns it
            return None
        if len(raw) != size:
            # Truncated shard: serving zeros would hand clients corrupt data.
            # Treat as unavailable so the remote/reconstruct fallback kicks in.
            return None
        return np.frombuffer(raw, dtype=np.uint8).copy()

    def _holder_key(self, shard_id: int) -> tuple:
        """Suspicion key for the holder behind `shard_id`. When the
        injected reader can name the peer (the volume server's closures
        carry a cache-only `peer_for` attribute), the key IS the peer
        identity — suspicion then applies to every shard of every volume
        that peer serves, so one wedged peer costs one capped attempt
        process-wide. Readers without peer identity fall back to a
        (volume, shard) key: the old per-volume scope, never wrong, just
        narrower."""
        peer_for = getattr(self.remote_reader, "peer_for", None)
        if peer_for is not None:
            try:
                peer = peer_for(shard_id)
            except Exception:  # noqa: BLE001 — identity is best-effort
                peer = None
            if peer:
                return ("peer", peer)
        return ("volume-shard", self.base, shard_id)

    def _holder_suspected(self, shard_id: int) -> bool:
        return self._suspicion.suspected(self._holder_key(shard_id))

    def _mark_holder_suspect(self, shard_id: int) -> None:
        self._suspicion.mark(self._holder_key(shard_id), self.recover_holder_backoff)

    def _track_wedged(self, shard_id: int, fut) -> None:
        """Remember that `fut` is a call into a wedged holder whose pool
        thread is still blocked; the holder reads as suspected until the
        call finally returns (SIGCONT, TCP reset, ...)."""
        self._suspicion.track_wedged(self._holder_key(shard_id), fut)

    def _remote_fetch_capped(
        self, shard_id: int, offset: int, size: int
    ) -> Optional[np.ndarray]:
        """One remote attempt under the per-holder cap: the call runs on
        the fetch pool and is abandoned once it has RUN for
        `recover_holder_timeout` — a SIGSTOPped/wedged holder (answers
        nothing, errors nothing) costs exactly one capped wait, gets
        marked suspect for the backoff window, and later reads skip it.
        The cap is measured from the call's ACTUAL start, same rule as
        the fan-out: an attempt stuck in the pool queue is the pool's
        fault, not the holder's, and must never suspect a healthy peer
        (the read gives up after ~2x the cap either way)."""
        if self.remote_reader is None or self._holder_suspected(shard_id):
            return None
        started: list[float] = []
        parent = trace_mod.current()

        def _call():
            started.append(_time.monotonic())
            with trace_mod.attach(parent), trace_mod.span(
                "ec.fetch", shard=shard_id
            ):
                return self.remote_reader(shard_id, offset, size)

        cap = self.recover_holder_timeout
        fut = self._fetch_executor().submit(_call)
        try:
            raw = fut.result(timeout=cap)
        except _FutureTimeout:
            if not started:
                # never left the queue: saturated pool, holder unproven —
                # a miss for this read, no suspicion
                stripe._abandon_future(fut)
                return None
            remaining = cap - (_time.monotonic() - started[0])
            raw = None
            if remaining > 0:
                try:
                    raw = fut.result(timeout=remaining)
                except _FutureTimeout:
                    remaining = 0.0
                except Exception:  # noqa: BLE001 — a down holder is a miss
                    return None
            if remaining <= 0:
                self._mark_holder_suspect(shard_id)
                self._track_wedged(shard_id, fut)
                stripe._abandon_future(fut)
                return None
        except Exception:  # noqa: BLE001 — a down holder is a miss,
            return None  # not a failed read: survivors can still serve it
        if raw is None:
            # a long-running NOTHING is the wedge signature when the
            # reader has its own internal transport timeout (it swallows
            # the stall and reports a miss): suspect without re-probing
            if (
                started
                and _time.monotonic() - started[0] >= self.recover_suspect_after
            ):
                self._mark_holder_suspect(shard_id)
            return None
        if started:
            # completed answers feed the per-peer latency EWMA the hedge
            # delay derives from; misses/wedges never do (see suspicion)
            self._suspicion.observe_latency(
                self._holder_key(shard_id), _time.monotonic() - started[0]
            )
        return np.frombuffer(raw, dtype=np.uint8).copy()

    def _read_present(self, shard_id: int, offset: int, size: int) -> Optional[np.ndarray]:
        """The non-degraded rungs of the read ladder (local -> remote), or
        None when the shard is unreachable and only reconstruction can
        serve the interval."""
        data = self._read_local(shard_id, offset, size)
        if data is not None:
            return data
        return self._remote_fetch_capped(shard_id, offset, size)

    def _read_shard_interval(self, shard_id: int, offset: int, size: int) -> np.ndarray:
        """One interval: local -> remote -> reconstruct-from-survivors."""
        data = self._read_present(shard_id, offset, size)
        if data is not None:
            return data
        return self._recover_interval(shard_id, offset, size)

    def _recover_interval(self, shard_id: int, offset: int, size: int) -> np.ndarray:
        """recoverOneRemoteEcShardInterval: read the same interval from every
        other shard and reconstruct the wanted one. Concurrent recovers of
        the SAME interval are single-flight coalesced (WEEDTPU_COALESCE_READS):
        a hot needle on a lost shard costs one survivor fan-out + decode,
        with every waiter handed a byte-identical copy."""
        t0 = _time.monotonic()
        trace_mod.set_class("degraded")
        try:
            with trace_mod.span("ec.recover", shard=shard_id, size=size):
                if not config.env("WEEDTPU_COALESCE_READS"):
                    return self._recover_interval_inner(shard_id, offset, size)
                return self._recover_interval_coalesced(shard_id, offset, size)
        finally:
            # DegradedReadSeconds is the CLIENT-facing latency (waiters
            # included); EcReconstructSeconds counts actual decodes and is
            # observed in _recover_interval_inner, else N coalesced waiters
            # would inflate the reconstruct histogram N-fold
            stats.DegradedReadSeconds.observe(_time.monotonic() - t0)

    def _recover_interval_coalesced(
        self, shard_id: int, offset: int, size: int
    ) -> np.ndarray:
        key = (shard_id, offset, size)
        with self._coalesce_lock:
            slot = self._coalesce.get(key)
            leader = slot is None
            if leader:
                slot = self._coalesce[key] = _CoalesceSlot()
        if not leader:
            stats.CoalescedReads.inc()
            # generous bound: the leader's decode is itself bounded by the
            # fetch deadline + one holder cap; a vanished leader (killed
            # thread) must not strand waiters forever
            budget = self.recover_fetch_deadline + self.recover_holder_timeout + 5.0
            with trace_mod.span("ec.coalesce.wait", shard=shard_id) as sp:
                won = slot.event.wait(timeout=budget)
                if sp is not None:
                    sp.annotate(served_by_leader=won)
            if won:
                if slot.error is not None:
                    raise slot.error
                assert slot.result is not None
                return slot.result.copy()
            return self._recover_interval_inner(shard_id, offset, size)
        try:
            out = self._recover_interval_inner(shard_id, offset, size)
            slot.result = out
            return out
        except BaseException as e:
            slot.error = e
            raise
        finally:
            # unpublish BEFORE waking waiters: a brand-new reader arriving
            # after the event must elect a fresh leader, never read a slot
            # that is mid-teardown
            with self._coalesce_lock:
                self._coalesce.pop(key, None)
            slot.event.set()

    def _fetch_executor(self) -> ThreadPoolExecutor:
        with self._fetch_pool_lock:
            if self._fetch_pool is None:
                self._fetch_pool = ThreadPoolExecutor(
                    max_workers=self.recover_fetch_parallelism,
                    thread_name_prefix=f"ec-fetch-{os.path.basename(self.base)}",
                )
            return self._fetch_pool

    def _recover_interval_inner(self, shard_id: int, offset: int, size: int) -> np.ndarray:
        t0 = _time.monotonic()
        try:
            shards = self._gather_survivors(shard_id, offset, size)
            with trace_mod.span(
                "ec.decode",
                backend=getattr(self.encoder, "backend", "?"),
                width=size,
            ):
                rec = self.encoder.reconstruct(shards, wanted=[shard_id])
            return rec[shard_id]
        finally:
            stats.EcReconstructSeconds.observe(_time.monotonic() - t0)

    def _gather_survivors(
        self, shard_id: int, offset: int, size: int
    ) -> list[Optional[np.ndarray]]:
        """Collect >= DATA_SHARDS survivor copies of one interval (local
        first, then a parallel remote fan-out). Raises IOError when too few
        survivors are reachable."""
        with trace_mod.span("ec.gather", shard=shard_id):
            return self._gather_survivors_fanout(shard_id, offset, size)

    def _gather_survivors_fanout(
        self, shard_id: int, offset: int, size: int
    ) -> list[Optional[np.ndarray]]:
        shards: list[Optional[np.ndarray]] = [None] * self.total_shards
        have = 0
        # local shards first — remote reads cost RTTs on the p50-critical path
        for s in range(self.total_shards):
            if s == shard_id or have >= self.data_shards:
                continue
            buf = self._read_local(s, offset, size)
            if buf is not None:
                shards[s] = buf
                have += 1
        need = self.data_shards - have
        attempted: tuple = ()
        deadline_expired = False
        if need > 0 and self.remote_reader is not None:
            # Fan out to ALL remaining survivors at once and take the first
            # `need` arrivals — the reference reads the same interval from
            # >=10 shards with parallel goroutines
            # (recoverOneRemoteEcShardInterval [ref: weed/storage/
            # store_ec.go — mount empty, SURVEY.md §3.2]); serial fetches
            # cost one RTT per survivor and dominated the reconstruct p50.
            # Late arrivals beyond `need` are ignored; a hung peer is cut by
            # the overall deadline rather than stalling the read forever.
            # suspected-wedged holders are skipped outright: the fan-out
            # needs only `need` of the remaining survivors, and a holder
            # inside its backoff window would just burn a pool thread
            candidates = []
            skipped_suspected = []
            for s in range(self.total_shards):
                if s == shard_id or shards[s] is not None:
                    continue
                if self._holder_suspected(s):
                    skipped_suspected.append(s)
                else:
                    candidates.append(s)
            trace_mod.annotate(
                local=have, need=need,
                **({"skipped_suspected": skipped_suspected}
                   if skipped_suspected else {}),
            )
            fan_parent = trace_mod.current()
            pool = self._fetch_executor()
            # per-holder cap is measured from each call's ACTUAL start (a
            # queued attempt waiting for a pool slot is not the holder's
            # fault): the worker records its entry time, and the wait loop
            # cuts any holder that has been RUNNING past the cap — wedged,
            # not merely slow — marking it suspect. The OVERALL read is
            # still bounded by `recover_fetch_deadline`, unchanged.
            started: dict[int, float] = {}
            attempted = tuple(self._holder_key(s) for s in candidates)

            def _attempt(s: int):
                started[s] = _time.monotonic()
                with trace_mod.attach(fan_parent), trace_mod.span(
                    "ec.fetch", shard=s
                ):
                    return self.remote_reader(s, offset, size)

            futs = {pool.submit(_attempt, s): s for s in candidates}
            primaries = {sid: fut for fut, sid in futs.items()}
            pending = set(futs)
            # hedging (WEEDTPU_HEDGE_READS): once a primary fetch has RUN
            # past the peer's EWMA-derived tail, launch ONE backup against
            # a different holder; first success wins, the loser is
            # cancelled/drained, and both results must be byte-identical.
            hedge_on = bool(config.env("WEEDTPU_HEDGE_READS"))
            hedge_started: dict[int, float] = {}
            # sid -> backup future, or None when a submit attempt found no
            # second holder (memoized: retrying every loop tick would spin
            # the wait budget down to 5 ms for the rest of the read)
            hedges: dict[int, object] = {}
            hedge_targets: dict[int, Optional[str]] = {}
            hedge_futs: set = set()
            hedge_wins: list[int] = []
            winners: dict[int, bytes] = {}
            deadline = _time.monotonic() + self.recover_fetch_deadline
            cap = self.recover_holder_timeout
            try:
                while pending and have < self.data_shards:
                    now = _time.monotonic()
                    for fut in list(pending):
                        sid = futs[fut]
                        is_hedge = fut in hedge_futs
                        t0s = (hedge_started if is_hedge else started).get(sid)
                        if t0s is None or fut.done():
                            continue
                        if now - t0s >= cap:
                            # running past the per-holder cap: wedged.
                            # Suspect it, remember the blocked thread, and
                            # stop waiting on it (the read may still
                            # complete from the other survivors). A wedged
                            # BACKUP blames the alternate holder it was
                            # pinned at — never the primary's key (which
                            # names a different, possibly healthy peer).
                            pending.discard(fut)
                            if is_hedge:
                                self._suspect_hedge_target(
                                    hedge_targets.get(sid), fut
                                )
                            else:
                                self._mark_holder_suspect(sid)
                                self._track_wedged(sid, fut)
                            stripe._abandon_future(fut)
                        elif (
                            hedge_on
                            and not is_hedge
                            and sid not in hedges
                            and now - t0s >= self._hedge_delay(sid)
                        ):
                            # memoize the outcome either way: None means
                            # "no second holder", and must not be retried
                            # (and re-pay peer lookups) every loop tick
                            hedges[sid] = self._submit_hedge(
                                pool, sid, offset, size,
                                hedge_started, hedge_targets,
                            )
                            backup = hedges[sid]
                            if backup is not None:
                                hedge_futs.add(backup)
                                futs[backup] = sid
                                pending.add(backup)
                    if not pending:
                        break
                    budget = deadline - now
                    if budget <= 0:
                        deadline_expired = True
                        break
                    # wake at the earliest per-holder cap OR pending hedge
                    # fire time, whichever comes first
                    wake: list[float] = []
                    for f in pending:
                        sid = futs[f]
                        is_hedge = f in hedge_futs
                        t0s = (hedge_started if is_hedge else started).get(sid)
                        if t0s is None:
                            continue
                        wake.append(t0s + cap - now)
                        if hedge_on and not is_hedge and sid not in hedges:
                            wake.append(t0s + self._hedge_delay(sid) - now)
                    if wake:
                        budget = min(budget, max(min(wake), 0.005))
                    done, pending = wait(
                        pending, timeout=budget, return_when=FIRST_COMPLETED
                    )
                    for fut in done:
                        sid = futs[fut]
                        is_hedge = fut in hedge_futs
                        try:
                            raw = fut.result()
                        except Exception:  # noqa: BLE001 — a failed peer is a miss
                            raw = None
                        t0s = (hedge_started if is_hedge else started).get(sid)
                        now2 = _time.monotonic()
                        if raw is not None and len(raw) == size:
                            if t0s is not None and not is_hedge:
                                # primaries only: a hedge's fast answer is
                                # the OTHER holder's latency and would drag
                                # the slow peer's estimate down
                                self._suspicion.observe_latency(
                                    self._holder_key(sid), now2 - t0s
                                )
                            want = winners.get(sid)
                            if want is not None:
                                # the hedged pair's LOSER also answered:
                                # first-success already won, but the bytes
                                # must agree — a divergence is survivor
                                # corruption, not a race to tolerate
                                if bytes(raw) != want:
                                    stats.DegradedReadErrors.labels(
                                        "HedgeMismatch"
                                    ).inc()
                                    raise IOError(
                                        f"shard {sid}: hedged fetch returned "
                                        "bytes differing from the primary's"
                                    )
                                continue
                            winners[sid] = bytes(raw)
                            shards[sid] = np.frombuffer(
                                raw, dtype=np.uint8
                            ).copy()
                            have += 1
                            if is_hedge:
                                stats.HedgeWon.inc()
                                hedge_wins.append(sid)
                            other = (
                                primaries.get(sid) if is_hedge else hedges.get(sid)
                            )
                            if other is not None and other in pending:
                                pending.discard(other)
                                self._settle_hedge_loser(other, winners[sid])
                        else:
                            # slow NOTHING = internally-timed-out wedge
                            # (see _remote_fetch_capped); fast None is a
                            # plain miss and never suspects. Same blame
                            # rule as the cap: a slow-missing BACKUP names
                            # its own alternate holder, not the primary.
                            if (
                                t0s is not None
                                and now2 - t0s >= self.recover_suspect_after
                            ):
                                if is_hedge:
                                    self._suspect_hedge_target(
                                        hedge_targets.get(sid), None
                                    )
                                else:
                                    self._mark_holder_suspect(sid)
            finally:
                fired = sorted(s for s, f in hedges.items() if f is not None)
                trace_mod.annotate(
                    gathered=have,
                    **({"hedges_fired": fired} if fired else {}),
                    **({"hedges_won": hedge_wins} if hedge_wins else {}),
                    **({"deadline_expired": True} if deadline_expired else {}),
                )
                # EVERY exit (normal, deadline, or an exception raised
                # mid-loop) cancels what never started and drains what did:
                # the discard callback drops a late result/exception on the
                # floor so a hung peer's thread never outlives the read with
                # a reference to its buffer (or an unobserved error).
                for fut in pending:
                    stripe._abandon_future(fut)
        if have < self.data_shards:
            suspected = tuple(
                self._holder_key(s)
                for s in range(self.total_shards)
                if s != shard_id and self._holder_suspected(s)
            )
            # the corruption class applies only when quarantine is actually
            # RELEVANT to this failure: the wanted shard itself sits
            # quarantined, or the quarantined shards are what kept the
            # survivor count short (with them clean the read would have had
            # enough). An unrelated quarantined shard during a plain
            # holder outage must still classify as holders-down.
            quarantine_blocked = bool(self.quarantined) and (
                shard_id in self.quarantined
                or (
                    not deadline_expired
                    and have + len(self.quarantined) >= self.data_shards
                )
            )
            if quarantine_blocked:
                # local shards sit quarantined for failed verification and
                # the stripe still couldn't be served: this is CORRUPTION
                # awaiting repair, not holders being down — a distinct
                # class (and retry hint) for clients and dashboards
                stats.DegradedReadErrors.labels(EcShardCorrupt.__name__).inc()
                raise EcShardCorrupt(
                    f"shard {shard_id}: only {have} clean surviving shards "
                    f"reachable, need {self.data_shards}; local shards "
                    f"{sorted(self.quarantined)} quarantined "
                    f"({self.quarantined}) — repair pending",
                    quarantined=self.quarantined,
                    shard_id=shard_id,
                    attempted=attempted,
                    suspected=suspected,
                )
            cls = EcDegradedReadTimeout if deadline_expired else EcNoViableHolders
            stats.DegradedReadErrors.labels(cls.__name__).inc()
            raise cls(
                f"shard {shard_id}: only {have} surviving shards reachable, "
                f"need {self.data_shards}"
                + (" (recover deadline expired)" if deadline_expired else ""),
                shard_id=shard_id,
                attempted=attempted,
                suspected=suspected,
            )
        return shards

    def _hedge_delay(self, shard_id: int) -> float:
        """Seconds a survivor fetch may run before its backup launches.
        WEEDTPU_HEDGE_DELAY_MS pins it; otherwise the per-peer latency
        EWMA (mean + 4*dev, a live high-quantile tracker) decides, with a
        cold-start default of half the slow-miss threshold. Never later
        than half the per-holder cap — past that the wedge machinery owns
        the fetch, not the hedge."""
        fixed = float(config.env("WEEDTPU_HEDGE_DELAY_MS"))
        if fixed > 0:
            return fixed / 1e3
        d = self._suspicion.hedge_delay(self._holder_key(shard_id))
        if d is None:
            d = max(0.05, self.recover_suspect_after / 2.0)
        return min(d, self.recover_holder_timeout / 2.0)

    def _submit_hedge(
        self, pool, shard_id: int, offset: int, size: int,
        hedge_started: dict[int, float],
        hedge_targets: dict[int, Optional[str]],
    ):
        """Launch the backup fetch for one survivor. Readers that expose
        holder addressing (`via` + `holders_for`, the volume server's
        closures) are steered at a DIFFERENT holder than the one the
        primary is inside; a reader without addressing re-runs its own
        holder ladder. None when there is no second holder to try.

        The backup rides the same bounded fetch pool as the primaries, so
        under heavy wedging it can queue before it runs — HedgeFired is
        therefore counted (and the per-holder cap armed) from the worker's
        ACTUAL start, never at submit."""
        reader = self.remote_reader
        if reader is None:
            return None
        via = getattr(reader, "via", None)
        holders_for = getattr(reader, "holders_for", None)
        target = None
        if via is not None and holders_for is not None:
            primary = None
            peer_for = getattr(reader, "peer_for", None)
            if peer_for is not None:
                try:
                    primary = peer_for(shard_id)
                except Exception:  # noqa: BLE001 — identity is best-effort
                    primary = None
            try:
                holders = list(holders_for(shard_id) or ())
            except Exception:  # noqa: BLE001 — no holder list, no hedge
                return None
            # skip holders already inside a suspicion window: pinning the
            # ONE backup at a known-wedged peer would spend the hedge on
            # exactly the holder it exists to route around
            alts = [
                a for a in holders
                if a != primary and not self._suspicion.suspected(("peer", a))
            ]
            if not alts:
                return None
            target = alts[0]
        hedge_targets[shard_id] = target
        parent = trace_mod.current()

        def _backup():
            hedge_started[shard_id] = _time.monotonic()
            stats.HedgeFired.inc()
            with trace_mod.attach(parent), trace_mod.span(
                "ec.hedge", shard=shard_id, **({"addr": target} if target else {})
            ):
                if target is not None:
                    return via(target, shard_id, offset, size)
                return reader(shard_id, offset, size)

        return pool.submit(_backup)

    def _suspect_hedge_target(self, target: Optional[str], fut) -> None:
        """Suspicion for a wedged/slow-missing BACKUP fetch: the blame key
        is the alternate holder the backup was pinned at (the peer-scoped
        key the registry shares process-wide). A backup without addressing
        (generic reader re-run) names no one — better unsuspected than the
        primary's key mis-marked for a different peer's wedge."""
        if not target:
            return
        key = ("peer", target)
        self._suspicion.mark(key, self.recover_holder_backoff)
        if fut is not None:
            self._suspicion.track_wedged(key, fut)

    def _settle_hedge_loser(self, fut, want: bytes) -> None:
        """First-success-wins settlement: cancel the loser if it never
        started; if running, drain it in the background and verify its
        late result byte-identical to the winner's (a mismatch is counted
        as HedgeMismatch — the read already returned the winner)."""
        if fut.cancel():
            return

        def _check(f):
            try:
                raw = f.result()
            except Exception:  # noqa: BLE001 — loser erred; winner served
                return
            if raw is not None and len(raw) == len(want) and bytes(raw) != want:
                stats.DegradedReadErrors.labels("HedgeMismatch").inc()

        fut.add_done_callback(_check)

    def _recover_intervals_batch(
        self, shard_id: int, items: list[tuple[int, int]]
    ) -> list[np.ndarray]:
        """Recover several (offset, size) intervals that all miss the SAME
        shard in one bucketed device call: survivors are gathered per
        interval (the same local -> remote ladder as the single path),
        grouped by which shards actually answered, zero-padded to a shared
        bucket length, and decoded as a (B, survivors, bucket) stack with
        ONE fused matrix per group — instead of one dispatch (and one
        decode-matrix application) per interval. Zero padding is exact and
        trimmed per interval before returning."""
        if len(items) == 1:
            off, size = items[0]
            return [self._recover_interval(shard_id, off, size)]
        t0 = _time.monotonic()
        trace_mod.set_class("degraded")
        try:
            with trace_mod.span(
                "ec.recover", shard=shard_id, batch=len(items)
            ):
                return self._recover_intervals_batch_inner(shard_id, items)
        finally:
            dt = _time.monotonic() - t0
            stats.EcReconstructSeconds.observe(dt)
            stats.DegradedReadSeconds.observe(dt)

    def _recover_intervals_batch_inner(
        self, shard_id: int, items: list[tuple[int, int]]
    ) -> list[np.ndarray]:
        gathered = [
            self._gather_survivors(shard_id, off, size) for off, size in items
        ]
        results: list[Optional[np.ndarray]] = [None] * len(items)
        # distinct survivor sets decode with distinct matrices; in the
        # common case (stable shard availability) there is ONE group
        groups: dict[tuple, list[int]] = {}
        for idx, shards in enumerate(gathered):
            present = tuple(
                i for i, s in enumerate(shards) if s is not None
            )[: self.data_shards]
            groups.setdefault(present, []).append(idx)
        for survivors, idxs in groups.items():
            nmax = max(items[i][1] for i in idxs)
            stack = np.zeros(
                (len(idxs), self.data_shards, nmax), dtype=np.uint8
            )
            for bi, i in enumerate(idxs):
                for di, s in enumerate(survivors):
                    arr = gathered[i][s]
                    stack[bi, di, : arr.shape[0]] = arr
            # bucketed: the encoder's own serving-path shape buckets,
            # so odd interval sizes never pay a fresh XLA compile
            with trace_mod.span(
                "ec.decode",
                backend=getattr(self.encoder, "backend", "?"),
                batch=len(idxs),
                width=nmax,
            ):
                out = self.encoder.reconstruct_batch(
                    stack, survivors, [shard_id], bucketed=True
                )
            for bi, i in enumerate(idxs):
                results[i] = np.ascontiguousarray(out[bi, 0, : items[i][1]])
        return results

    def read_intervals(self, intervals: list[locate_mod.Interval]) -> bytes:
        """Read every interval, batching the ones that need reconstruction:
        intervals that miss the same shard become ONE bucketed device call
        instead of a blocking reconstruct each (a multi-interval needle on
        a degraded volume previously paid the full decode ladder per
        interval)."""
        parts: list[Optional[bytes]] = [None] * len(intervals)
        recover: dict[int, list[tuple[int, int, int]]] = {}  # sid -> [(i, off, size)]
        for i, iv in enumerate(intervals):
            shard_id, off = iv.to_shard_id_and_offset(self.large, self.small)
            data = self._read_present(shard_id, off, iv.size)
            if data is not None:
                parts[i] = data.tobytes()
            else:
                recover.setdefault(shard_id, []).append((i, off, iv.size))
        for shard_id, missed in recover.items():
            recs = self._recover_intervals_batch(
                shard_id, [(off, size) for _, off, size in missed]
            )
            for (i, _, _), arr in zip(missed, recs):
                parts[i] = arr.tobytes()
        return b"".join(parts)

    def read_needle_blob(self, needle_id: int) -> bytes:
        """The raw on-disk needle record (ReadEcShardNeedle minus parsing)."""
        _, _, intervals = self.locate_needle(needle_id)
        # an EC-volume read starts as intact; a reconstructing interval
        # upgrades the trace class to "degraded" inside the recover path
        if trace_mod.current_class() == "healthy":
            trace_mod.set_class("ec_intact")
        return self.read_intervals(intervals)

    # -- deletes -------------------------------------------------------------

    def delete_needle(self, needle_id: int) -> bool:
        """Append to the deletion journal (VolumeEcBlobDelete semantics).
        Returns False (and journals nothing) when the needle is absent or
        already deleted, matching Volume.delete_needle."""
        try:
            self.find_needle_from_ecx(needle_id)
        except (NeedleNotFound, NeedleDeleted):
            return False
        stripe.append_ecj(self.base, needle_id)
        self._deleted.add(needle_id)
        return True
