"""EcVolume — the serving-side view of one EC volume's shard set.

Mirror of weed/storage/erasure_coding/ec_volume.go + the read path of
weed/storage/store_ec.go (ReadEcShardNeedle / readEcShardIntervals /
recoverOneRemoteEcShardInterval) [VERIFY: mount empty; SURVEY.md §3.2].

Needle lookup: binary search of the sorted .ecx (vectorized: the index is
loaded once into a numpy structured array and searched with searchsorted).
Interval reads are delegated to the volume's ReadPlanner (see
`read_planner.py`), which owns the per-interval decision tree: local shard
files, the decoded-interval cache, the injected remote reader (capped,
hedged, suspicion-laddered), and reconstruction from >=10 surviving
shards — the degraded-read path whose p50 latency is a north-star metric.
This module keeps the storage-shaped state: index, shard handles,
quarantine registry, geometry, and the deletion journal.
"""

from __future__ import annotations

import os
import threading
import zlib
from typing import Callable, Optional

import numpy as np

from seaweedfs_tpu.obs import trace as trace_mod

from seaweedfs_tpu.ec import locate as locate_mod
from seaweedfs_tpu.ec import read_planner as read_planner_mod
from seaweedfs_tpu.ec import stripe
from seaweedfs_tpu.ec import suspicion as suspicion_mod
from seaweedfs_tpu.ec.constants import (
    ERASURE_CODING_LARGE_BLOCK_SIZE,
    ERASURE_CODING_SMALL_BLOCK_SIZE,
)

# the typed read errors and the coalesce slot moved to read_planner with
# the decision tree; re-exported here because callers (volume server,
# shell, tests) historically import them from ec_volume
from seaweedfs_tpu.ec.read_planner import (  # noqa: F401 — re-exports
    EcDegradedReadError,
    EcDegradedReadTimeout,
    EcNoViableHolders,
    EcShardCorrupt,
    _CoalesceSlot,
)
from seaweedfs_tpu.ops.rs_codec import Encoder, new_encoder
from seaweedfs_tpu.storage import idx as idx_mod
from seaweedfs_tpu.storage import types

# remote_reader(shard_id, offset, size) -> bytes | None
RemoteReader = Callable[[int, int, int], Optional[bytes]]


class NeedleNotFound(KeyError):
    pass


class NeedleDeleted(Exception):
    pass


class EcGeometryError(ValueError):
    """The on-disk shard set contradicts the .eci-recorded geometry —
    shard ids past the recorded total, or a shard file longer than the
    layout allows. Mounting anyway would silently mis-map every interval
    (before geometry validation, a wrong-geometry shard set was only
    caught by CRC luck on the first degraded read). Typed so the volume
    server can refuse the mount loudly and discovery can skip the volume
    instead of serving garbage."""

    def __init__(self, msg: str, base: str = "", details: Optional[dict] = None):
        super().__init__(msg)
        self.base = base
        #: machine-readable mismatch description (shard ids / sizes)
        self.details = dict(details or {})


class EcVolume:
    def __init__(
        self,
        base_file_name: str,
        encoder: Optional[Encoder] = None,
        large_block_size: int = ERASURE_CODING_LARGE_BLOCK_SIZE,
        small_block_size: int = ERASURE_CODING_SMALL_BLOCK_SIZE,
        remote_reader: Optional[RemoteReader] = None,
        version: int = 3,
        shard_size: Optional[int] = None,
        warm_on_mount: bool = True,
        ecj_compact_threshold: int = 1 << 20,
        recover_fetch_parallelism: int = 8,
        recover_fetch_deadline: float = 30.0,
        recover_holder_timeout: float = 30.0,
        recover_holder_backoff: float = 30.0,
        recover_suspect_after: float = 5.0,
        suspicion: Optional[suspicion_mod.HolderSuspicion] = None,
    ):
        self.base = base_file_name
        self.encoder = encoder or new_encoder()
        self.remote_reader = remote_reader
        self.version = version
        # degraded-read survivor fan-out (lazily built: most volumes never
        # take a reconstructing read, and a pool per mount would leak threads)
        self.recover_fetch_parallelism = recover_fetch_parallelism
        self.recover_fetch_deadline = recover_fetch_deadline
        # per-HOLDER cap + suspicion window: a WEDGED holder (SIGSTOPped
        # process, dead NIC — it neither answers nor errors) is cut at
        # `recover_holder_timeout` per attempt, then skipped entirely for
        # `recover_holder_backoff` seconds, so one wedged peer costs the
        # ladder ONE capped attempt — not a per-read stall — and the
        # serving p50 returns to healthy levels until the window expires.
        # The cap default (30 s) deliberately exceeds the volume server's
        # remote_reader internals (per-holder 10 s transport timeout x a
        # couple of replica holders): a reader mid-failover to a healthy
        # replica must never be aborted and suspected by this layer. The
        # cap's hard cut matters for readers WITHOUT internal timeouts.
        # `recover_suspect_after` is the complementary soft signal: a
        # remote fetch that runs at least this long and still yields
        # NOTHING (the shape of a reader whose internal timeout swallowed
        # a wedged peer) marks the shard suspect — a genuine miss (shard
        # simply absent) answers None fast and is never suspected.
        self.recover_holder_timeout = recover_holder_timeout
        self.recover_holder_backoff = recover_holder_backoff
        self.recover_suspect_after = recover_suspect_after
        # suspicion state lives in a PROCESS-WIDE registry keyed by peer
        # identity when the reader can name peers (see _holder_key): a
        # wedged peer serving many volumes costs one capped attempt
        # process-wide, not one per volume
        self._suspicion = suspicion if suspicion is not None else suspicion_mod.GLOBAL
        # the planner owns the per-interval decision tree (read ladder,
        # coalescing, hedging, fetch pool, decoded-interval cache rung);
        # it reads this volume's mutable collaborators live
        self.planner = read_planner_mod.ReadPlanner(self)
        # recorded stripe geometry (.eci) wins over constructor defaults —
        # opening shards with the wrong geometry would mis-map every interval
        info = stripe.read_ec_info(base_file_name)
        if info is not None:
            self.large = int(info["large_block_size"])
            self.small = int(info["small_block_size"])
        else:
            self.large = large_block_size
            self.small = small_block_size
        # code geometry: recorded in the .eci for geometry-flexible volumes
        # (ec.convert targets), implied legacy 10+4 otherwise. The serving
        # encoder must MATCH it — a caller-supplied encoder of a different
        # geometry is replaced by a same-backend sibling, never trusted to
        # decode a layout it does not describe.
        self.geometry = stripe.geometry_from_info(info)
        self.data_shards = self.geometry.data_shards
        self.total_shards = self.geometry.total_shards
        self.encoder = stripe.encoder_for_info(info, self.encoder)

        # mount-time journal compaction: a delete-heavy volume's .ecj is
        # folded into .ecx tombstones once it crosses the threshold, so the
        # journal (and its replay cost) stays bounded over the volume's life
        ecj_path = base_file_name + ".ecj"
        if (
            ecj_compact_threshold
            and os.path.exists(ecj_path)
            and os.path.getsize(ecj_path) >= ecj_compact_threshold
        ):
            stripe.compact_ecj(base_file_name)

        with open(base_file_name + ".ecx", "rb") as f:
            self._index = idx_mod.index_entries_array(f.read())
        self._keys = self._index["key"]
        self._deleted = set(stripe.read_ecj(base_file_name))

        self._shard_files = {}
        # shards pulled out of serving by failed integrity verification:
        # {shard_id: reason} ("corrupt" | "truncated" | "missing"). The
        # serving handle is closed (reads route local -> remote ->
        # reconstruct around it) and VolumeStatus surfaces the entry so
        # rebuilding peers and operators see WHY the shard is gone.
        self.quarantined: dict[int, str] = {}
        self.shard_size = shard_size or 0
        try:
            self._validate_geometry(info)
            for s in range(self.total_shards):
                p = stripe.shard_file_name(base_file_name, s)
                if os.path.exists(p):
                    # weedlint: ignore[open-no-ctx] serving handles owned by the volume, closed in close()
                    self._shard_files[s] = open(p, "rb")
                    self.shard_size = max(self.shard_size, os.path.getsize(p))
        except BaseException:
            for f in self._shard_files.values():
                f.close()
            self._shard_files.clear()
            raise
        if self.shard_size == 0 and remote_reader is not None and len(self._index):
            # No local shard to size the volume from: large-vs-small row math
            # would silently mis-map offsets, so demand an explicit size.
            raise ValueError(
                "EcVolume with no local shards needs an explicit shard_size "
                "to locate blocks correctly"
            )
        # The locate math only needs the large-row count; shard_size * D is a
        # consistent stand-in for the true .dat size (ev.DatFileSize analog);
        # the recorded exact size wins when available.
        if info is not None:
            self.dat_file_size = int(info["dat_size"])
        else:
            self.dat_file_size = self.shard_size * self.data_shards

        # resident hot path (SURVEY §7.3.5): pre-build the serving-path
        # decode matrices and pre-compile the bucketed reconstruct shapes in
        # the background so the first degraded client read is warm; join
        # `warm_thread` to wait for it (tests/bench)
        self.warm_thread: Optional[threading.Thread] = None
        if warm_on_mount:
            self.warm_thread = threading.Thread(target=self._warm, daemon=True)
            self.warm_thread.start()

    def _validate_geometry(self, info: Optional[dict]) -> None:
        """Mount-time shard-count/geometry consistency gate: the local
        shard set must FIT the .eci-recorded (or legacy-implied) geometry.
        Stray shard ids past the recorded total, or a shard file longer
        than the recorded layout allows, mean the files and the sidecar
        describe different codes — reading on would silently mis-map
        intervals (previously only caught by CRC luck), so the mount
        raises typed EcGeometryError instead."""
        # a journaled-but-unfinished conversion cut-over means `.eci` and
        # the shard files may describe DIFFERENT geometries (the .eci
        # swaps first; the journal is unlinked last) — and when the two
        # layouts' shard sizes coincide, neither the stray-id nor the
        # over-length check below can tell. Refuse until the convert
        # resume path finishes the swap.
        from seaweedfs_tpu.ec import convert as convert_mod

        if convert_mod.pending_cutover(self.base):
            raise EcGeometryError(
                f"{self.base}: conversion cut-over in progress (journaled "
                "intent, swap unfinished) — resume `ec.convert` to finish "
                "the swap before mounting",
                base=self.base,
                details={"pending_cutover": True},
            )
        stray = [
            s
            for s in stripe.find_local_shards(self.base)
            if s >= self.total_shards
        ]
        if stray:
            raise EcGeometryError(
                f"{self.base}: shard files {stray} exceed the recorded "
                f"{self.geometry.family} geometry "
                f"({self.data_shards}+{self.geometry.parity_shards}) — "
                "wrong-geometry shard set?",
                base=self.base,
                details={"stray_shards": stray, "family": self.geometry.family},
            )
        if info is None:
            return  # legacy sidecar-less set: sizes are unvouchable
        n_large, n_small = stripe.stripe_layout(
            int(info["dat_size"]), self.large, self.small, self.data_shards
        )
        expected = n_large * self.large + n_small * self.small
        over = {
            s: os.path.getsize(stripe.shard_file_name(self.base, s))
            for s in stripe.find_local_shards(self.base, self.total_shards)
            if os.path.getsize(stripe.shard_file_name(self.base, s)) > expected
        }
        if over:
            # over-length is a GEOMETRY contradiction (a truncated shard is
            # bit-rot/crash damage and stays the scrub ladder's business)
            raise EcGeometryError(
                f"{self.base}: shard files longer than the recorded layout "
                f"allows ({over} > {expected} bytes for "
                f"{self.geometry.family}) — wrong-geometry shard set?",
                base=self.base,
                details={"over_length": over, "expected_size": expected},
            )

    def _warm(self) -> None:
        try:
            self.encoder.warm_decode_matrices(local_shards=self.shard_ids)
            self.encoder.warm_reconstruct()
        except Exception:  # noqa: BLE001 — warmup must never break a mount
            pass

    def close(self) -> None:
        for f in self._shard_files.values():
            f.close()
        self._shard_files.clear()
        # unmount forgets this volume's (volume, shard)-scoped suspicion —
        # a remount must not inherit stale windows (peer-scoped windows
        # persist: they describe the peer, not this volume)
        self._suspicion.forget_volume(self.base)
        # close() is THE cut-over seam: Store.mount_ec_volume (remount)
        # and unmount_ec_volume (ec.convert cut-over, shard moves) both
        # route through it, so the next mount of this base can never see
        # decoded intervals from the previous file set
        read_planner_mod.CACHE.invalidate_volume(self.base)
        self.planner.shutdown()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    @property
    def shard_ids(self) -> list[int]:
        return sorted(self._shard_files)

    def verify_local_shards(self) -> Optional[dict]:
        """Check every locally-held shard file against the CRC32s the
        streaming encode recorded in the .eci sidecar (and rebuilds verify
        on write) — the fsck-style integrity pass for a mounted EC volume.
        Returns {shard_id: ok} or None when the volume predates CRC
        recording (no shard_crc32 in the sidecar)."""
        info = stripe.read_ec_info(self.base)
        recorded = (info or {}).get("shard_crc32")
        if not isinstance(recorded, list) or len(recorded) != self.total_shards:
            return None
        out = {}
        for s in sorted(self._shard_files):
            # private handle per shard: the serving handles in
            # self._shard_files are seek/read'd by concurrent interval
            # reads, and an fsck pass sharing them would race both sides
            with open(stripe.shard_file_name(self.base, s), "rb") as f:
                crc = 0
                while True:
                    chunk = f.read(1 << 20)
                    if not chunk:
                        break
                    crc = zlib.crc32(chunk, crc)
            out[s] = crc == recorded[s]
        return out

    def drop_local_shard(self, shard_id: int) -> bool:
        """Stop serving a shard from local disk (single-shard unmount /
        shard-file loss): closes the handle so reads fall through to the
        remote -> reconstruct ladder."""
        f = self._shard_files.pop(shard_id, None)
        if f is None:
            return False
        f.close()
        return True

    def quarantine_shard(self, shard_id: int, reason: str = "corrupt") -> bool:
        """Pull a shard that failed integrity verification out of serving:
        the handle closes (degraded reads route around it instead of
        decoding garbage into a client response) and the reason is
        remembered for VolumeStatus / the typed EcShardCorrupt error.
        Returns whether a serving handle was actually dropped."""
        self.quarantined[shard_id] = str(reason)
        # a quarantined shard means bytes this volume served (and decodes
        # derived from them) may have been corrupt: flush the WHOLE
        # volume's cached intervals, not just this shard's — survivor
        # sets that included the bad local copy produced the others
        read_planner_mod.CACHE.invalidate_volume(self.base)
        return self.drop_local_shard(shard_id)

    def mount_local_shard(self, shard_id: int) -> bool:
        """(Re)open one shard file for serving — the repair path's remount
        after a quarantined shard was rebuilt and re-verified. Clears the
        quarantine entry. False when the file does not exist."""
        p = stripe.shard_file_name(self.base, shard_id)
        try:
            # weedlint: ignore[open-no-ctx] serving handle owned by the volume, closed in close()
            f = open(p, "rb")
        except OSError:
            return False
        old = self._shard_files.pop(shard_id, None)
        if old is not None:
            old.close()
        self._shard_files[shard_id] = f
        self.shard_size = max(self.shard_size, os.path.getsize(p))
        self.quarantined.pop(shard_id, None)
        # the freshly-(re)mounted file is now authoritative for this
        # shard: decoded intervals cached before the rebuild landed must
        # not outlive it
        read_planner_mod.CACHE.invalidate_shard(self.base, shard_id)
        return True

    # -- index ---------------------------------------------------------------

    def find_needle_from_ecx(self, needle_id: int) -> tuple[int, int]:
        """-> (actual_byte_offset, size). Raises NeedleNotFound/NeedleDeleted."""
        pos = int(np.searchsorted(self._keys, np.uint64(needle_id)))
        if pos >= len(self._keys) or int(self._keys[pos]) != needle_id:
            raise NeedleNotFound(needle_id)
        entry = self._index[pos]
        size = int(entry["size"])
        if types.is_deleted(size) or needle_id in self._deleted:
            raise NeedleDeleted(needle_id)
        return types.offset_to_actual(int(entry["offset"])), size

    def locate_needle(self, needle_id: int) -> tuple[int, int, list[locate_mod.Interval]]:
        """LocateEcShardNeedle: -> (offset, size, intervals covering the full
        on-disk record: header + body + checksum [+ts] + padding)."""
        offset, size = self.find_needle_from_ecx(needle_id)
        whole = types.actual_size(size, self.version)
        intervals = locate_mod.locate_data(
            self.large, self.small, self.dat_file_size, offset, whole,
            self.data_shards,
        )
        return offset, size, intervals

    # -- interval reads ------------------------------------------------------

    def _read_local(self, shard_id: int, offset: int, size: int) -> Optional[np.ndarray]:
        f = self._shard_files.get(shard_id)
        if f is None:
            return None
        try:
            f.seek(offset)
            raw = f.read(size)
        except (ValueError, OSError):
            # handle closed underneath us (concurrent quarantine/unmount)
            # or the disk faulted mid-read: both mean "this local copy is
            # unavailable", and the remote/reconstruct ladder owns it
            return None
        if len(raw) != size:
            # Truncated shard: serving zeros would hand clients corrupt data.
            # Treat as unavailable so the remote/reconstruct fallback kicks in.
            return None
        return np.frombuffer(raw, dtype=np.uint8).copy()

    # -- planner delegation ----------------------------------------------------
    # The decision tree (suspicion ladder, capped/hedged fetches,
    # coalescing, batched reconstruction, the decoded-interval cache rung)
    # lives on self.planner; these shims keep the long-standing EcVolume
    # surface that the volume server, shell, and tests call.

    def _holder_suspected(self, shard_id: int) -> bool:
        return self.planner.holder_suspected(shard_id)

    def _mark_holder_suspect(self, shard_id: int) -> None:
        self.planner.mark_holder_suspect(shard_id)

    def _remote_fetch_capped(
        self, shard_id: int, offset: int, size: int
    ) -> Optional[np.ndarray]:
        return self.planner._remote_fetch_capped(shard_id, offset, size)

    def _read_present(self, shard_id: int, offset: int, size: int) -> Optional[np.ndarray]:
        return self.planner.read_present(shard_id, offset, size)

    def _read_shard_interval(self, shard_id: int, offset: int, size: int) -> np.ndarray:
        """One interval: local -> cache -> remote -> reconstruct."""
        return self.planner.read_interval(shard_id, offset, size)

    def _recover_interval(self, shard_id: int, offset: int, size: int) -> np.ndarray:
        return self.planner.recover_interval(shard_id, offset, size)

    def _gather_survivors(
        self, shard_id: int, offset: int, size: int
    ) -> list[Optional[np.ndarray]]:
        return self.planner._gather_survivors(shard_id, offset, size)

    def _hedge_delay(self, shard_id: int) -> float:
        return self.planner.hedge_delay(shard_id)

    def _recover_intervals_batch(
        self, shard_id: int, items: list[tuple[int, int]]
    ) -> list[np.ndarray]:
        return self.planner.recover_intervals_batch(shard_id, items)

    def read_intervals(self, intervals: list[locate_mod.Interval]) -> bytes:
        """Read every interval, batching the ones that need reconstruction:
        intervals that miss the same shard become ONE bucketed device call
        instead of a blocking reconstruct each (a multi-interval needle on
        a degraded volume previously paid the full decode ladder per
        interval)."""
        parts: list[Optional[bytes]] = [None] * len(intervals)
        recover: dict[int, list[tuple[int, int, int]]] = {}  # sid -> [(i, off, size)]
        for i, iv in enumerate(intervals):
            shard_id, off = iv.to_shard_id_and_offset(self.large, self.small)
            data = self.planner.read_present(shard_id, off, iv.size)
            if data is not None:
                parts[i] = data.tobytes()
            else:
                recover.setdefault(shard_id, []).append((i, off, iv.size))
        for shard_id, missed in recover.items():
            recs = self.planner.recover_intervals_batch(
                shard_id, [(off, size) for _, off, size in missed]
            )
            for (i, _, _), arr in zip(missed, recs):
                parts[i] = arr.tobytes()
        return b"".join(parts)

    def read_needle_blob(self, needle_id: int) -> bytes:
        """The raw on-disk needle record (ReadEcShardNeedle minus parsing)."""
        _, _, intervals = self.locate_needle(needle_id)
        # an EC-volume read starts as intact; a reconstructing interval
        # upgrades the trace class to "degraded" inside the recover path
        if trace_mod.current_class() == "healthy":
            trace_mod.set_class("ec_intact")
        return self.read_intervals(intervals)

    # -- deletes -------------------------------------------------------------

    def delete_needle(self, needle_id: int) -> bool:
        """Append to the deletion journal (VolumeEcBlobDelete semantics).
        Returns False (and journals nothing) when the needle is absent or
        already deleted, matching Volume.delete_needle."""
        try:
            self.find_needle_from_ecx(needle_id)
        except (NeedleNotFound, NeedleDeleted):
            return False
        stripe.append_ecj(self.base, needle_id)
        self._deleted.add(needle_id)
        return True
