"""Process-wide holder-suspicion registry for degraded EC reads.

PR 4 gave each EcVolume a per-holder cap + suspicion window so a wedged
peer (SIGSTOPped process, dead NIC) costs one capped attempt instead of
a per-read stall. But the window was keyed per-VOLUME by shard id: one
wedged peer serving shards of many volumes was rediscovered — one capped
attempt plus one parked pool thread — once per volume. This registry is
the fix: suspicion state lives here, shared by every EcVolume in the
process, and is keyed by PEER IDENTITY whenever the volume's
remote_reader can name the peer behind a shard (the `peer_for` attribute
the volume server attaches to its reader closures). A wedged peer then
costs ONE capped attempt process-wide among volumes whose holder
locations are known (live attempt, completed-read history, or the
server's location cache); a volume whose holders were never looked up
cannot name the peer without a master round-trip — which the check path
must never pay — so its first degraded read still spends one capped
attempt before converging on the shared peer key. Volumes whose readers
cannot name peers at all fall back to a (volume, shard) key, which
reproduces the old per-volume behavior exactly.

Keys are opaque tuples built by EcVolume._holder_key; this registry only
stores and expires them.
"""

from __future__ import annotations

import threading
import time as _time
from typing import Optional


class HolderSuspicion:
    """Thread-safe map of suspicion keys -> backoff expiry, plus the
    wedged-inflight set (keys whose capped attempt is STILL blocked inside
    a reader — suspected past any backoff expiry, so a second pool thread
    is never stacked onto the same wedged peer)."""

    #: EWMA gains, Jacobson/Karels (the TCP RTO estimator): the mean moves
    #: at 1/8 per sample, the deviation at 1/4 — smooth enough to ignore
    #: one outlier, live enough to follow a peer that turns slow
    _LAT_ALPHA = 0.125
    _LAT_BETA = 0.25
    #: hedge delay ~ mean + 4*dev: for near-normal latency that tracks
    #: beyond p99, so a hedge fires on genuine stragglers, not jitter
    _LAT_K = 4.0
    #: below this many samples the estimate is noise, not evidence
    _LAT_MIN_SAMPLES = 3

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._until: dict[tuple, float] = {}
        self._wedged: dict[tuple, object] = {}
        # per-key fetch-latency estimator: (ewma, ewdev, samples). Fed by
        # every COMPLETED remote fetch on the degraded ladder; read by the
        # hedging logic to decide when a running fetch has outlived the
        # peer's own tail and deserves a backup against another holder.
        self._lat: dict[tuple, tuple[float, float, int]] = {}

    def suspected(self, key: tuple) -> bool:
        with self._lock:
            until = self._until.get(key)
            if until is not None:
                if until > _time.monotonic():
                    return True
                # expired: prune on sight — this registry outlives every
                # volume, so dead keys must not ride along for the life
                # of the server
                del self._until[key]
            return key in self._wedged

    def mark(self, key: tuple, backoff: float) -> None:
        """Start (or extend) the suspicion window for `key`."""
        with self._lock:
            now = _time.monotonic()
            # marks are rare (one per wedge discovery): sweep the whole
            # map here so churn in peers/volumes can never grow it
            # unboundedly between checks
            for k in [k for k, t in self._until.items() if t <= now]:
                del self._until[k]
            self._until[key] = now + backoff

    def track_wedged(self, key: tuple, fut) -> None:
        """Remember that `fut` is a call into a wedged holder whose pool
        thread is still blocked; the key reads as suspected until the call
        finally returns (SIGCONT, TCP reset, ...)."""
        with self._lock:
            self._wedged[key] = fut

        def _clear(f, _k=key):
            with self._lock:
                if self._wedged.get(_k) is f:
                    del self._wedged[_k]

        fut.add_done_callback(_clear)

    # -- per-peer fetch latency (feeds the hedge delay) ----------------------

    def observe_latency(self, key: tuple, seconds: float) -> None:
        """Feed one completed remote-fetch duration into `key`'s estimator.
        Failures and abandoned (capped) attempts must NOT be fed: the
        estimator models the peer answering, and a wedge is the suspicion
        window's job, not a data point on the latency curve."""
        if seconds < 0:
            return
        with self._lock:
            prev = self._lat.get(key)
            if prev is None:
                # first sample: seed the deviation at half the mean, the
                # classic RTO bootstrap, so one sample never yields a
                # zero-width (hair-trigger) hedge delay
                self._lat[key] = (seconds, seconds / 2.0, 1)
                return
            ewma, ewdev, n = prev
            err = seconds - ewma
            ewma += self._LAT_ALPHA * err
            ewdev += self._LAT_BETA * (abs(err) - ewdev)
            self._lat[key] = (ewma, ewdev, n + 1)

    def latency_estimate(self, key: tuple) -> Optional[tuple[float, float, int]]:
        """(ewma_seconds, ewdev_seconds, samples) or None when unknown."""
        with self._lock:
            return self._lat.get(key)

    def hedge_delay(
        self, key: tuple, floor: float = 0.002, ceiling: float = 30.0
    ) -> Optional[float]:
        """EWMA-derived delay before a backup fetch against another holder:
        mean + K*dev (a live high-quantile tracker). None until the key has
        `_LAT_MIN_SAMPLES` completed fetches — hedging on no evidence would
        just double every cold volume's fan-out."""
        with self._lock:
            est = self._lat.get(key)
        if est is None or est[2] < self._LAT_MIN_SAMPLES:
            return None
        ewma, ewdev, _ = est
        return min(ceiling, max(floor, ewma + self._LAT_K * ewdev))

    def forget_volume(self, base: str) -> None:
        """Drop the (volume, shard)-scoped fallback keys for one volume —
        called from EcVolume.close() so an unmount/remount cycle starts
        with a clean slate (the pre-registry behavior, where suspicion
        died with the instance). PEER-scoped windows persist on purpose:
        they describe the peer process, not this volume, and are bounded
        by the backoff window either way."""
        with self._lock:
            for d in (self._until, self._wedged, self._lat):
                for k in [
                    k for k in d
                    if k[0] == "volume-shard" and len(k) > 1 and k[1] == base
                ]:
                    del d[k]

    def reset(self) -> None:
        """Drop all state (test isolation: ports get reused across test
        servers, and a stale peer key must not leak suspicion forward)."""
        with self._lock:
            self._until.clear()
            self._wedged.clear()
            self._lat.clear()


#: the process-wide default every EcVolume shares unless handed its own
GLOBAL = HolderSuspicion()
