"""S3 gateway — S3-compatible REST API over the filer, mirror of
weed/s3api/ [VERIFY: mount empty; SURVEY.md §2.1 "S3 gateway" row, §1 L6].

  auth.py   — AWS Signature V4 verification + identity/action access
              control (s3api/auth_credentials.go, auth_signature_v4.go)
  server.py — S3ApiServer: bucket/object/multipart REST handlers
              (s3api/s3api_server.go, s3api_bucket_handlers.go,
              s3api_object_handlers.go, filer_multipart.go)

Buckets live under /buckets/<name> in the filer namespace, as in the
reference. Object data flows through the filer HTTP API (which chunks to
the volume tier); metadata ops (listings, multipart assembly by
chunk-list splicing) go over the filer RPC service.
"""

from seaweedfs_tpu.s3api.auth import Iam, Identity, sign_request
from seaweedfs_tpu.s3api.server import S3ApiServer

__all__ = ["Iam", "Identity", "sign_request", "S3ApiServer"]
