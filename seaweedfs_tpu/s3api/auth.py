"""AWS Signature V4 + identity access control — mirror of
weed/s3api/auth_signature_v4.go and auth_credentials.go [VERIFY: mount
empty; SURVEY.md §2.1 "S3 gateway" row].

Identities come from the s3 config (the reference's `-s3.config` JSON /
filer-stored identities): each has credentials and a list of actions,
optionally bucket-scoped ("Read:bucketname"). With no identities
configured the gateway is open (anonymous Admin), matching the
reference's default dev behavior.

`sign_request` is the client half (used by tests and the S3 replication
sink) so signatures are verified against an independent implementation
of the same spec.
"""

from __future__ import annotations

import calendar
import hashlib
import hmac
import json
import time
import urllib.parse
from dataclasses import dataclass, field
from typing import Optional

_MAX_SKEW_S = 15 * 60  # SigV4 replay window

ACTION_ADMIN = "Admin"
ACTION_READ = "Read"
ACTION_WRITE = "Write"
ACTION_LIST = "List"

_ALGO = "AWS4-HMAC-SHA256"


@dataclass
class Identity:
    name: str
    access_key: str
    secret_key: str
    actions: list[str] = field(default_factory=lambda: [ACTION_ADMIN])

    def can_do(self, action: str, bucket: str = "") -> bool:
        for a in self.actions:
            if a == ACTION_ADMIN:
                return True
            base, _, scope = a.partition(":")
            if base != action:
                continue
            if not scope or scope == bucket:
                return True
        return False


class Iam:
    """Identity set + SigV4 verifier."""

    def __init__(self, identities: Optional[list[Identity]] = None):
        self.identities = list(identities or [])

    @classmethod
    def from_config(cls, conf: dict) -> "Iam":
        """Parse the reference's s3 config shape:
        {"identities": [{"name": ..., "credentials": [{"accessKey": ...,
        "secretKey": ...}], "actions": ["Read", "Write:bucket"]}]}"""
        ids = []
        for d in conf.get("identities", []):
            for cred in d.get("credentials", []):
                ids.append(
                    Identity(
                        name=d.get("name", cred.get("accessKey", "")),
                        access_key=cred.get("accessKey", ""),
                        secret_key=cred.get("secretKey", ""),
                        actions=list(d.get("actions", [ACTION_ADMIN])),
                    )
                )
        return cls(ids)

    @property
    def open(self) -> bool:
        return not self.identities

    def lookup(self, access_key: str) -> Optional[Identity]:
        if not access_key:  # credential-less users (revoked keys) never match
            return None
        for i in self.identities:
            if i.access_key == access_key:
                return i
        return None

    def add(self, identity: Identity) -> None:
        self.identities = [
            i for i in self.identities if i.access_key != identity.access_key
        ] + [identity]

    def remove(self, access_key: str) -> None:
        self.identities = [i for i in self.identities if i.access_key != access_key]

    # -- verification ---------------------------------------------------------

    def authenticate(
        self,
        method: str,
        path: str,
        query: str,
        headers: dict[str, str],
        payload: bytes,
        expect_service: Optional[str] = None,
        expect_hosts: Optional[set[str]] = None,
    ) -> tuple[Optional[Identity], str]:
        """Returns (identity, "") on success or (None, error_code).
        Error codes follow S3: AccessDenied / InvalidAccessKeyId /
        SignatureDoesNotMatch / MissingSecurityHeader.

        expect_service pins the credential scope's service field (s3/iam)
        so a request signed for one endpoint class cannot be replayed
        verbatim against another within the skew window; expect_hosts pins
        the signed `host` header to the server's own advertised names."""
        payload_decl = headers.get("x-amz-content-sha256", "")
        if payload_decl.startswith("STREAMING-"):
            # aws-chunked framing is never decoded — reject on open
            # gateways too, or the framing bytes get stored as data
            return None, "NotImplemented"
        if self.open:
            return Identity("anonymous", "", "", [ACTION_ADMIN]), ""
        qparams = dict(urllib.parse.parse_qsl(query, keep_blank_values=True))
        if "X-Amz-Signature" in qparams:
            # presigned URL: SigV4 in the query string, not the headers
            return self._authenticate_presigned(
                method, path, query, headers, qparams, expect_service, expect_hosts
            )
        auth = headers.get("authorization", "")
        if not auth.startswith(_ALGO):
            return None, "MissingSecurityHeader"
        try:
            fields = dict(
                kv.strip().split("=", 1)
                for kv in auth[len(_ALGO) :].strip().split(",")
            )
            cred = fields["Credential"]
            signed_headers = fields["SignedHeaders"].split(";")
            got_sig = fields["Signature"]
            access_key, date, region, service, _ = cred.split("/", 4)
        except (KeyError, ValueError):
            return None, "AuthorizationHeaderMalformed"
        if expect_service is not None and service != expect_service:
            # scope mismatch: signed for a different endpoint class
            return None, "AccessDenied"
        # the signature must bind the target endpoint or a captured
        # request verifies verbatim against any other server sharing the
        # identity set
        if "host" not in signed_headers:
            return None, "InvalidRequest"
        # Host is case-insensitive per RFC 9110 §4.2.3; expect_hosts is
        # pre-lowercased by the servers at construction
        if expect_hosts is not None and headers.get("host", "").lower() not in expect_hosts:
            return None, "AccessDenied"
        identity = self.lookup(access_key)
        if identity is None:
            return None, "InvalidAccessKeyId"
        amz_date = headers.get("x-amz-date", "")
        try:
            req_ts = calendar.timegm(time.strptime(amz_date, "%Y%m%dT%H%M%SZ"))
        except ValueError:
            return None, "AccessDenied"
        if abs(time.time() - req_ts) > _MAX_SKEW_S:  # replayed/stale request
            return None, "RequestTimeTooSkewed"
        payload_hash = payload_decl
        # AWS requires x-amz-content-sha256 on every signed S3 request,
        # and it must itself be signed: an absent or unsigned header lets
        # a captured signature be replayed with a substituted body
        if not payload_hash:
            return None, "MissingSecurityHeader"
        if "x-amz-content-sha256" not in signed_headers:
            return None, "InvalidRequest"
        if payload_hash != "UNSIGNED-PAYLOAD":
            if hashlib.sha256(payload).hexdigest() != payload_hash:
                return None, "XAmzContentSHA256Mismatch"
        want = _signature(
            identity.secret_key,
            method,
            path,
            query,
            headers,
            signed_headers,
            payload_hash,
            amz_date,
            region,
            service,
        )
        if not hmac.compare_digest(want, got_sig):
            return None, "SignatureDoesNotMatch"
        return identity, ""


    _PRESIGN_MAX_EXPIRES = 7 * 24 * 3600  # AWS's 7-day ceiling

    def _authenticate_presigned(
        self,
        method: str,
        path: str,
        query: str,
        headers: dict[str, str],
        qparams: dict[str, str],
        expect_service: Optional[str],
        expect_hosts: Optional[set[str]],
    ) -> tuple[Optional[Identity], str]:
        """Query-string SigV4 (presigned URLs): the payload is always
        UNSIGNED-PAYLOAD and X-Amz-Signature is excluded from the canonical
        query. Expiry comes from X-Amz-Date + X-Amz-Expires."""
        if qparams.get("X-Amz-Algorithm") != _ALGO:
            return None, "AuthorizationQueryParametersError"
        try:
            cred = qparams["X-Amz-Credential"]
            amz_date = qparams["X-Amz-Date"]
            expires = int(qparams["X-Amz-Expires"])
            signed_headers = qparams["X-Amz-SignedHeaders"].split(";")
            got_sig = qparams["X-Amz-Signature"]
            access_key, date, region, service, _ = cred.split("/", 4)
        except (KeyError, ValueError):
            return None, "AuthorizationQueryParametersError"
        if not 1 <= expires <= self._PRESIGN_MAX_EXPIRES:
            return None, "AuthorizationQueryParametersError"
        if expect_service is not None and service != expect_service:
            return None, "AccessDenied"
        if "host" not in signed_headers:
            return None, "InvalidRequest"
        if expect_hosts is not None and headers.get("host", "").lower() not in expect_hosts:
            return None, "AccessDenied"
        identity = self.lookup(access_key)
        if identity is None:
            return None, "InvalidAccessKeyId"
        try:
            req_ts = calendar.timegm(time.strptime(amz_date, "%Y%m%dT%H%M%SZ"))
        except ValueError:
            return None, "AccessDenied"
        now = time.time()
        if now < req_ts - _MAX_SKEW_S:
            return None, "AccessDenied"  # from the future beyond clock skew
        if now > req_ts + expires:
            return None, "AccessDenied"  # expired link
        # canonical query = every parameter EXCEPT the signature itself
        filtered = "&".join(
            part
            for part in query.split("&")
            if part and not part.startswith("X-Amz-Signature=")
        )
        want = _signature(
            identity.secret_key,
            method,
            path,
            filtered,
            headers,
            signed_headers,
            "UNSIGNED-PAYLOAD",
            amz_date,
            region,
            service,
        )
        if not hmac.compare_digest(want, got_sig):
            return None, "SignatureDoesNotMatch"
        return identity, ""


# -- SigV4 math (shared by verifier and client signer) ------------------------


def _canonical_query(query: str) -> str:
    if not query:
        return ""
    pairs = []
    for part in query.split("&"):
        if not part:
            continue
        k, _, v = part.partition("=")
        pairs.append((urllib.parse.quote(urllib.parse.unquote_plus(k), safe="-_.~"),
                      urllib.parse.quote(urllib.parse.unquote_plus(v), safe="-_.~")))
    return "&".join(f"{k}={v}" for k, v in sorted(pairs))


def _signature(
    secret: str,
    method: str,
    path: str,
    query: str,
    headers: dict[str, str],
    signed_headers: list[str],
    payload_hash: str,
    amz_date: str,
    region: str,
    service: str,
) -> str:
    canonical_headers = "".join(
        f"{h}:{' '.join(headers.get(h, '').split())}\n" for h in signed_headers
    )
    canonical = "\n".join(
        [
            method,
            urllib.parse.quote(path, safe="/-_.~"),
            _canonical_query(query),
            canonical_headers,
            ";".join(signed_headers),
            payload_hash,
        ]
    )
    scope = f"{amz_date[:8]}/{region}/{service}/aws4_request"
    string_to_sign = "\n".join(
        [_ALGO, amz_date, scope, hashlib.sha256(canonical.encode()).hexdigest()]
    )
    k = f"AWS4{secret}".encode()
    for part in (amz_date[:8], region, service, "aws4_request"):
        k = hmac.new(k, part.encode(), hashlib.sha256).digest()
    return hmac.new(k, string_to_sign.encode(), hashlib.sha256).hexdigest()


def sign_request(
    access_key: str,
    secret_key: str,
    method: str,
    url: str,
    payload: bytes = b"",
    region: str = "us-east-1",
    service: str = "s3",
    extra_headers: Optional[dict[str, str]] = None,
) -> dict[str, str]:
    """Build signed headers for an S3 request (client side)."""
    u = urllib.parse.urlparse(url)
    # the verifier canonicalizes the DECODED path; sign the same view or
    # any percent-encoded key double-encodes and never matches
    path = urllib.parse.unquote(u.path or "/")
    amz_date = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
    payload_hash = hashlib.sha256(payload).hexdigest()
    headers = {
        "host": u.netloc,
        "x-amz-date": amz_date,
        "x-amz-content-sha256": payload_hash,
        **{k.lower(): v for k, v in (extra_headers or {}).items()},
    }
    signed = sorted(headers)
    sig = _signature(
        secret_key,
        method,
        path,
        u.query,
        headers,
        signed,
        payload_hash,
        amz_date,
        region,
        service,
    )
    scope = f"{amz_date[:8]}/{region}/{service}/aws4_request"
    headers["authorization"] = (
        f"{_ALGO} Credential={access_key}/{scope}, "
        f"SignedHeaders={';'.join(signed)}, Signature={sig}"
    )
    return headers


def presign_url(
    access_key: str,
    secret_key: str,
    method: str,
    url: str,
    expires: int = 3600,
    region: str = "us-east-1",
    service: str = "s3",
) -> str:
    """Client half of presigned URLs: returns `url` with the SigV4 query
    parameters appended. The holder of the link can perform `method` on
    the object until expiry, with no credentials of their own."""
    u = urllib.parse.urlparse(url)
    path = urllib.parse.unquote(u.path or "/")
    amz_date = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
    scope = f"{amz_date[:8]}/{region}/{service}/aws4_request"
    params = [
        ("X-Amz-Algorithm", _ALGO),
        ("X-Amz-Credential", f"{access_key}/{scope}"),
        ("X-Amz-Date", amz_date),
        ("X-Amz-Expires", str(int(expires))),
        ("X-Amz-SignedHeaders", "host"),
    ]
    base_q = [p for p in (u.query or "").split("&") if p]
    query = "&".join(
        base_q + [f"{k}={urllib.parse.quote(v, safe='-_.~')}" for k, v in params]
    )
    sig = _signature(
        secret_key,
        method,
        path,
        query,
        {"host": u.netloc},
        ["host"],
        "UNSIGNED-PAYLOAD",
        amz_date,
        region,
        service,
    )
    return u._replace(query=query + f"&X-Amz-Signature={sig}").geturl()


# -- identity persistence (filer KV) ------------------------------------------

_KV_KEY = "s3_identities"


def save_identities(kv, iam: Iam) -> None:
    """Persist the identity set through any object with kv_put (a
    FilerClient) — the seam the IAM API writes and the S3 gateway reads."""
    conf = {
        "identities": [
            {
                "name": i.name,
                "credentials": [{"accessKey": i.access_key, "secretKey": i.secret_key}],
                "actions": i.actions,
            }
            for i in iam.identities
        ]
    }
    kv.kv_put(_KV_KEY, json.dumps(conf).encode())


def load_identities(kv) -> Optional[Iam]:
    raw = kv.kv_get(_KV_KEY)
    if not raw:
        return None
    try:
        return Iam.from_config(json.loads(raw.decode()))
    except ValueError:  # malformed KV must not kill auth plumbing
        return None
