"""S3ApiServer — bucket/object/multipart REST handlers over the filer,
mirror of weed/s3api/s3api_server.go, s3api_bucket_handlers.go,
s3api_object_handlers.go, s3api_object_handlers_multipart.go,
filer_multipart.go [VERIFY: mount empty; SURVEY.md §2.1 "S3 gateway"].

Wire layout matches the reference: buckets are filer directories under
/buckets/<name>; multipart uploads stage parts under
/buckets/.uploads/<bucket>/<uploadId>/ and Complete splices the parts'
chunk lists into the final entry WITHOUT copying data (the reference
does the same chunk-list surgery in filer_multipart.go).

Data plane: proxied through the filer HTTP API (chunking to the volume
tier happens there). Metadata plane: filer RPC.

Supported: ListBuckets, Create/Delete/HeadBucket, ListObjectsV1/V2
(prefix, delimiter, marker/continuation, max-keys), Put/Get/Head/Delete
Object (+Range), CopyObject, DeleteObjects (bulk XML), multipart
lifecycle (initiate/uploadPart/complete/abort/listParts), SigV4 auth.

Listing order note: keys stream in directory-DFS order (names sorted per
directory), which differs from strict full-key lexicographic order only
when a sibling name extends a directory name with a byte < '/'.
"""

from __future__ import annotations

import hashlib
import io
import json
import re
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
import uuid
import xml.etree.ElementTree as ET
from typing import Iterator, Optional

from seaweedfs_tpu import stats
from seaweedfs_tpu.filer.client import FilerClient
from seaweedfs_tpu.filer.entry import Entry
from seaweedfs_tpu.s3api.auth import (
    ACTION_LIST,
    ACTION_READ,
    ACTION_WRITE,
    ACTION_ADMIN,
    Iam,
    load_identities,
    save_identities,
)
from seaweedfs_tpu.utils import httpd
from seaweedfs_tpu.security import tls

BUCKETS_ROOT = "/buckets"
UPLOADS_ROOT = "/buckets/.uploads"
_XMLNS = "http://s3.amazonaws.com/doc/2006-03-01/"

# uploadIds are minted as uuid4().hex by _initiate_multipart; anything
# else in the query string is attacker-controlled path material (an
# unvalidated id containing '..' walks out of the staging area and can
# delete a victim bucket via AbortMultipartUpload)
_UPLOAD_ID_RE = re.compile(r"^[0-9a-f]{32}$")


def _valid_path(bucket: str, key: str) -> bool:
    """Reject bucket/key pairs whose filer path would normalize outside
    /buckets/<bucket>/ — '.'/'..'/empty segments and dot-prefixed bucket
    names (which would collide with the .uploads staging area)."""
    if bucket.startswith("."):
        return False
    segs = key.split("/") if key else []
    if any(s in ("", ".", "..") for s in segs[:-1]):
        return False
    # a single trailing "" segment is a folder-marker key ("a/b/")
    return not (segs and segs[-1] in (".", ".."))


class S3ApiServer:
    def __init__(
        self,
        filer_http_address: str,
        filer_grpc_address: str,
        port: int = 0,
        host: str = "127.0.0.1",
        iam: Optional[Iam] = None,
        extra_hosts: Optional[set[str]] = None,
    ):
        self.filer_http = filer_http_address
        self.filer = FilerClient(filer_grpc_address)
        self.iam = iam or Iam()
        # additional advertised host:port names (LB/proxy fronts) accepted
        # as the signed `host` header besides this server's own url;
        # pre-lowercased here so the per-request compare is a set lookup
        self.extra_hosts = {h.lower() for h in (extra_hosts or ())}
        self._iam_checked_at = 0.0
        self.host = host
        self._http = _ThreadingHTTPServer((host, port), _Handler)
        tls.maybe_wrap_https(self._http)  # data-path HTTPS when configured
        self._http.s3_server = self
        self.port = self._http.server_address[1]
        self.extra_hosts |= {f"{h}:{self.port}" for h in httpd.loopback_aliases(host)}
        self._thread = threading.Thread(target=self._http.serve_forever, daemon=True)

    @property
    def url(self) -> str:
        return f"{self.host}:{self.port}"

    def start(self) -> None:
        # ensure the buckets root exists
        from seaweedfs_tpu.filer.entry import Entry as _E

        if self.filer.lookup(BUCKETS_ROOT) is None:
            self.filer.create(_E(path=BUCKETS_ROOT, is_directory=True))
        # seed the filer KV (the cluster-wide identity root the IAM API
        # serves) with the file-configured identities: otherwise the IAM
        # API sees an empty KV, stays in its open bootstrap window, and
        # an unauthenticated caller can mint an admin this gateway would
        # honor on its next KV reload
        if not self.iam.open:
            existing = load_identities(self.filer)
            if existing is None or not any(
                i.access_key for i in existing.identities
            ):
                save_identities(self.filer, self.iam)
        self._thread.start()

    def stop(self) -> None:
        self._http.shutdown()
        self._http.server_close()
        self.filer.close()

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()

    # -- filer helpers --------------------------------------------------------

    def bucket_path(self, bucket: str) -> str:
        return f"{BUCKETS_ROOT}/{bucket}"

    def object_path(self, bucket: str, key: str) -> str:
        return f"{BUCKETS_ROOT}/{bucket}/{key}"

    def filer_url(self, path: str, query: str = "") -> str:
        enc = urllib.parse.quote(path)
        return f"{tls.scheme()}://{self.filer_http}{enc}" + (f"?{query}" if query else "")

    def walk_keys(
        self, bucket: str, prefix: str = "", after: str = ""
    ) -> Iterator[Entry]:
        """Yield file entries under the bucket whose key starts with
        `prefix` and sorts after `after`, in directory-DFS order. The
        `after` marker is pushed down into per-directory listings so a
        paginated walk costs O(depth × page), not a full re-walk."""
        root = self.bucket_path(bucket)

        def rec(dir_path: str, base: str) -> Iterator[Entry]:
            # base = key prefix of this directory ("" at the bucket root,
            # else "a/b/"). Resume the listing at the marker's component.
            start, include = "", False
            if after and after.startswith(base) and len(after) > len(base):
                start = after[len(base) :].split("/", 1)[0]
                include = True
            while True:
                batch = self.filer.list(
                    dir_path, start_from=start, include_start=include, limit=256
                )
                include = False
                if not batch:
                    return
                for e in batch:
                    key = e.path[len(root) + 1 :]
                    if e.is_directory:
                        probe = key + "/"
                        if after and after > probe and not after.startswith(probe):
                            continue  # whole subtree sorts before the marker
                        # descend only where the subtree can match prefix
                        if probe.startswith(prefix) or prefix.startswith(probe):
                            yield from rec(e.path, probe)
                    elif key.startswith(prefix) and (not after or key > after):
                        yield e
                start = batch[-1].name

        yield from rec(root, "")


# -- HTTP --------------------------------------------------------------------


class _ThreadingHTTPServer(httpd.ThreadingHTTPServer):
    s3_server: "S3ApiServer"


def _xml(tag: str, ns: bool = True) -> ET.Element:
    e = ET.Element(tag)
    if ns:
        e.set("xmlns", _XMLNS)
    return e


def _sub(parent: ET.Element, tag: str, text: Optional[str] = None) -> ET.Element:
    e = ET.SubElement(parent, tag)
    if text is not None:
        e.text = text
    return e


def _render(root: ET.Element) -> bytes:
    return b'<?xml version="1.0" encoding="UTF-8"?>\n' + ET.tostring(root)


def _iso(ts: float) -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%S.000Z", time.gmtime(ts))


class _Handler(httpd.QuietHandler):
    @property
    def s3(self) -> S3ApiServer:
        return self.server.s3_server

    # -- plumbing -------------------------------------------------------------

    def _parse(self) -> Optional[tuple[str, str, dict]]:
        """Parse /bucket/key?query. Returns None (after replying 400) for
        paths with '.'/'..'/empty segments — the filer normalizes paths,
        so an un-rejected '..' would let a bucket-scoped identity escape
        its bucket (the reference validates object names the same way)."""
        u = urllib.parse.urlparse(self.path)
        parts = urllib.parse.unquote(u.path).lstrip("/").split("/", 1)
        bucket = parts[0]
        key = parts[1] if len(parts) > 1 else ""
        if not _valid_path(bucket, key):
            self._error(400, "InvalidArgument", "invalid bucket or object name")
            return None
        q = {k: v[0] for k, v in urllib.parse.parse_qs(u.query, keep_blank_values=True).items()}
        return bucket, key, q

    def _body(self) -> Optional[bytes]:
        body = self.read_body()
        if body is None:
            self.reply_length_required()
        return body

    def _reply(self, code: int, body: bytes = b"", ctype="application/xml", headers=None):
        self.send_reply(code, body, ctype, headers=headers)

    def _error(self, code: int, s3_code: str, message: str = ""):
        root = _xml("Error", ns=False)
        _sub(root, "Code", s3_code)
        _sub(root, "Message", message or s3_code)
        self._reply(code, _render(root))

    def _auth(self, action: str, bucket: str, payload: bytes):
        """Authenticate + authorize; returns the resolved Identity (truthy)
        or None after replying 403/501 — callers needing a second
        authorization check (CopyObject's source-bucket Read) reuse the
        identity instead of re-verifying the signature."""
        u = urllib.parse.urlparse(self.path)
        headers = {k.lower(): v for k, v in self.headers.items()}
        path = urllib.parse.unquote(u.path) or "/"
        expect_hosts = {self.s3.url.lower()} | self.s3.extra_hosts
        if self.s3.iam.open:
            # an open gateway must notice identities minted via the IAM
            # API and start enforcing auth (throttled KV poll)
            now = time.monotonic()
            if now - self.s3._iam_checked_at > 5.0:
                self.s3._iam_checked_at = now
                fresh = load_identities(self.s3.filer)
                if fresh is not None and fresh.identities:
                    self.s3.iam.identities = fresh.identities
        identity, err = self.s3.iam.authenticate(
            self.command, path, u.query, headers, payload,
            expect_service="s3", expect_hosts=expect_hosts,
        )
        if identity is None and err == "NotImplemented":
            self._error(501, "NotImplemented", "aws-chunked (STREAMING-*) uploads not supported")
            return None
        if identity is None and err == "InvalidAccessKeyId":
            # the IAM API may have minted new credentials since start:
            # reload the persisted identity set once and retry
            fresh = load_identities(self.s3.filer)
            if fresh is not None and fresh.identities:
                self.s3.iam.identities = fresh.identities
                identity, err = self.s3.iam.authenticate(
                    self.command, path, u.query, headers, payload,
                    expect_service="s3", expect_hosts=expect_hosts,
                )
        if identity is None:
            self._error(403, err)
            return None
        if not identity.can_do(action, bucket):
            self._error(403, "AccessDenied", f"no {action} on {bucket}")
            return None
        return identity

    # -- dispatch -------------------------------------------------------------

    def do_GET(self):
        parsed = self._parse()
        if parsed is None:
            return
        bucket, key, q = parsed
        if not bucket:
            stats.S3RequestCounter.labels("ListBuckets").inc()
            if self._auth(ACTION_LIST, "", b""):
                self._list_buckets()
            return
        if not key:
            if "uploadId" in q:
                self._error(404, "NoSuchUpload")
                return
            if "location" in q:
                stats.S3RequestCounter.labels("GetBucketLocation").inc()
                if self._auth(ACTION_READ, bucket, b""):
                    if self.s3.filer.lookup(self.s3.bucket_path(bucket)) is None:
                        self._error(404, "NoSuchBucket")
                    else:  # single-region deployment: the us-east-1 form
                        self._reply(200, _render(_xml("LocationConstraint")))
                return
            if "acl" in q:
                stats.S3RequestCounter.labels("GetBucketAcl").inc()
                if self._auth(ACTION_READ, bucket, b""):
                    if self.s3.filer.lookup(self.s3.bucket_path(bucket)) is None:
                        self._error(404, "NoSuchBucket")
                    else:
                        self._get_acl()
                return
            stats.S3RequestCounter.labels("ListObjects").inc()
            if self._auth(ACTION_LIST, bucket, b""):
                self._list_objects(bucket, q)
            return
        if "uploadId" in q:
            stats.S3RequestCounter.labels("ListParts").inc()
            if self._auth(ACTION_READ, bucket, b""):
                self._list_parts(bucket, key, q["uploadId"])
            return
        if "tagging" in q:
            stats.S3RequestCounter.labels("GetObjectTagging").inc()
            if self._auth(ACTION_READ, bucket, b""):
                self._get_tagging(bucket, key)
            return
        if "acl" in q:
            stats.S3RequestCounter.labels("GetObjectAcl").inc()
            if self._auth(ACTION_READ, bucket, b""):
                if self._lookup_object(bucket, key) is not None:
                    self._get_acl()
            return
        stats.S3RequestCounter.labels("GetObject").inc()
        if self._auth(ACTION_READ, bucket, b""):
            self._get_object(bucket, key, head=False)

    def do_HEAD(self):
        parsed = self._parse()
        if parsed is None:
            return
        bucket, key, q = parsed
        if not key:
            if self._auth(ACTION_READ, bucket, b""):
                if self.s3.filer.lookup(self.s3.bucket_path(bucket)) is None:
                    self._reply(404)
                else:
                    self._reply(200)
            return
        if self._auth(ACTION_READ, bucket, b""):
            self._get_object(bucket, key, head=True)

    def do_PUT(self):
        parsed = self._parse()
        if parsed is None:
            return
        bucket, key, q = parsed
        body = self._body()
        if body is None:
            return
        if "acl" in q:
            # PutBucketAcl/PutObjectAcl: accepted and ignored — access
            # control is identity-based here; SDKs setting canned ACLs
            # must not fail their whole upload flow on a 501. Existence is
            # still checked so a failed-upload + put_object_acl sequence
            # 404s like AWS instead of reporting false success.
            stats.S3RequestCounter.labels("PutAcl").inc()
            if self._auth(ACTION_WRITE, bucket, body):
                if self.s3.filer.lookup(self.s3.bucket_path(bucket)) is None:
                    self._error(404, "NoSuchBucket")
                elif key and self.s3.filer.lookup(
                    self.s3.object_path(bucket, key)
                ) is None:
                    self._error(404, "NoSuchKey", key)
                else:
                    self._reply(200)
            return
        if not key:
            stats.S3RequestCounter.labels("CreateBucket").inc()
            if self._auth(ACTION_ADMIN, bucket, body):
                self._create_bucket(bucket)
            return
        if "partNumber" in q and "uploadId" in q:
            stats.S3RequestCounter.labels("UploadPart").inc()
            identity = self._auth(ACTION_WRITE, bucket, body)
            if identity:
                self._upload_part(bucket, key, q, body, identity)
            return
        if "tagging" in q:
            stats.S3RequestCounter.labels("PutObjectTagging").inc()
            if self._auth(ACTION_WRITE, bucket, body):
                self._put_tagging(bucket, key, body)
            return
        stats.S3RequestCounter.labels("PutObject").inc()
        identity = self._auth(ACTION_WRITE, bucket, body)
        if identity is None:
            return
        src = self.headers.get("x-amz-copy-source", "")
        if src:
            self._copy_object(bucket, key, src, identity)
        else:
            self._put_object(bucket, key, body)

    def do_POST(self):
        parsed = self._parse()
        if parsed is None:
            return
        bucket, key, q = parsed
        body = self._body()
        if body is None:
            return
        if not key and "delete" in q:
            stats.S3RequestCounter.labels("DeleteObjects").inc()
            if self._auth(ACTION_WRITE, bucket, body):
                self._delete_objects(bucket, body)
            return
        if key and "uploads" in q:
            stats.S3RequestCounter.labels("CreateMultipartUpload").inc()
            if self._auth(ACTION_WRITE, bucket, body):
                self._initiate_multipart(bucket, key)
            return
        if key and "uploadId" in q:
            stats.S3RequestCounter.labels("CompleteMultipartUpload").inc()
            if self._auth(ACTION_WRITE, bucket, body):
                self._complete_multipart(bucket, key, q["uploadId"], body)
            return
        self._error(400, "InvalidRequest")

    def do_DELETE(self):
        parsed = self._parse()
        if parsed is None:
            return
        bucket, key, q = parsed
        if not key:
            stats.S3RequestCounter.labels("DeleteBucket").inc()
            if self._auth(ACTION_ADMIN, bucket, b""):
                self._delete_bucket(bucket)
            return
        if "uploadId" in q:
            stats.S3RequestCounter.labels("AbortMultipartUpload").inc()
            if self._auth(ACTION_WRITE, bucket, b""):
                self._abort_multipart(bucket, key, q["uploadId"])
            return
        if "tagging" in q:
            stats.S3RequestCounter.labels("DeleteObjectTagging").inc()
            if self._auth(ACTION_WRITE, bucket, b""):
                self._delete_tagging(bucket, key)
            return
        stats.S3RequestCounter.labels("DeleteObject").inc()
        if self._auth(ACTION_WRITE, bucket, b""):
            self._delete_object(bucket, key)

    # -- buckets --------------------------------------------------------------

    def _list_buckets(self):
        root = _xml("ListAllMyBucketsResult")
        owner = _sub(root, "Owner")
        _sub(owner, "ID", "weedtpu")
        buckets = _sub(root, "Buckets")
        for e in self.s3.filer.list(BUCKETS_ROOT, limit=10000):
            if not e.is_directory or e.name.startswith("."):
                continue
            b = _sub(buckets, "Bucket")
            _sub(b, "Name", e.name)
            _sub(b, "CreationDate", _iso(e.attributes.crtime))
        self._reply(200, _render(root))

    def _create_bucket(self, bucket):
        from seaweedfs_tpu.filer.entry import Entry as _E

        if self.s3.filer.lookup(self.s3.bucket_path(bucket)) is not None:
            self._error(409, "BucketAlreadyExists")
            return
        self.s3.filer.create(_E(path=self.s3.bucket_path(bucket), is_directory=True))
        self._reply(200, headers={"Location": f"/{bucket}"})

    def _delete_bucket(self, bucket):
        path = self.s3.bucket_path(bucket)
        if self.s3.filer.lookup(path) is None:
            self._error(404, "NoSuchBucket")
            return
        if self.s3.filer.list(path, limit=1):
            self._error(409, "BucketNotEmpty")
            return
        self.s3.filer.delete(path, recursive=True)
        try:
            # in-flight multipart staging references needles in this
            # bucket's collection; dropping the collection without it
            # would leave staged entries pointing at dead volumes
            self.s3.filer.delete(f"{UPLOADS_ROOT}/{bucket}", recursive=True)
        except Exception:  # noqa: BLE001 — no staged uploads
            pass
        try:
            # per-bucket collections: drop the bucket's volumes so the
            # space (incl. tombstoned needles) comes back immediately
            self.s3.filer.delete_collection(bucket)
        except Exception:  # noqa: BLE001 — reclamation is best-effort;
            pass  # auto-vacuum collects stragglers later
        self._reply(204)

    # -- listing --------------------------------------------------------------

    def _list_objects(self, bucket, q):
        if self.s3.filer.lookup(self.s3.bucket_path(bucket)) is None:
            self._error(404, "NoSuchBucket")
            return
        v2 = q.get("list-type") == "2"
        prefix = q.get("prefix", "")
        delimiter = q.get("delimiter", "")
        max_keys = httpd.safe_int(q.get("max-keys"), 1000)
        after = q.get("start-after", "") or q.get("marker", "")
        token = q.get("continuation-token", "")
        if token:
            after = token

        contents: list[Entry] = []
        common: list[str] = []
        seen_common = set()
        truncated = False
        next_after = ""
        # a continuation token can point INSIDE a prefix group already
        # emitted on the previous page — skip the rest of that group or
        # the CommonPrefix would repeat across pages
        skip_group = ""
        if after and delimiter and after.startswith(prefix):
            rest = after[len(prefix) :]
            d = rest.find(delimiter)
            if d >= 0:
                skip_group = prefix + rest[: d + len(delimiter)]
        for e in self.s3.walk_keys(bucket, prefix, after=after):
            key = e.path[len(self.s3.bucket_path(bucket)) + 1 :]
            if skip_group and key.startswith(skip_group):
                continue
            if delimiter:
                rest = key[len(prefix) :]
                d = rest.find(delimiter)
                if d >= 0:
                    cp = prefix + rest[: d + len(delimiter)]
                    if cp not in seen_common:
                        if len(contents) + len(seen_common) >= max_keys:
                            truncated = True
                            break
                        seen_common.add(cp)
                        common.append(cp)
                        next_after = key
                    continue
            if len(contents) + len(seen_common) >= max_keys:
                truncated = True
                break
            contents.append(e)
            next_after = key

        root = _xml("ListBucketResult")
        _sub(root, "Name", bucket)
        _sub(root, "Prefix", prefix)
        _sub(root, "MaxKeys", str(max_keys))
        _sub(root, "IsTruncated", "true" if truncated else "false")
        if delimiter:
            _sub(root, "Delimiter", delimiter)
        if v2:
            _sub(root, "KeyCount", str(len(contents) + len(common)))
            if truncated:
                _sub(root, "NextContinuationToken", next_after)
        elif truncated:
            _sub(root, "NextMarker", next_after)
        for e in contents:
            key = e.path[len(self.s3.bucket_path(bucket)) + 1 :]
            c = _sub(root, "Contents")
            _sub(c, "Key", key)
            _sub(c, "LastModified", _iso(e.attributes.mtime))
            _sub(c, "ETag", f'"{e.attributes.md5 or ""}"')
            _sub(c, "Size", str(e.size))
            _sub(c, "StorageClass", "STANDARD")
        for cp in common:
            p = _sub(root, "CommonPrefixes")
            _sub(p, "Prefix", cp)
        self._reply(200, _render(root))

    # -- objects --------------------------------------------------------------

    def _put_object(self, bucket, key, body):
        if self.s3.filer.lookup(self.s3.bucket_path(bucket)) is None:
            self._error(404, "NoSuchBucket")
            return
        headers = {
            "Content-Type": self.headers.get("Content-Type", "application/octet-stream")
        }
        for k, v in self.headers.items():
            if k.lower().startswith("x-amz-meta-"):
                headers[k] = v
        tagging = self.headers.get(self.TAGS_KEY, "")
        if tagging:
            pairs = urllib.parse.parse_qsl(tagging, keep_blank_values=True)
            if len(pairs) > self.MAX_TAGS:
                self._error(400, "BadRequest", f"up to {self.MAX_TAGS} tags allowed")
                return
            headers[self.TAGS_KEY] = tagging  # filer stores x-amz-* in extended
        req = urllib.request.Request(
            self.s3.filer_url(self.s3.object_path(bucket, key)),
            data=body,
            method="PUT",
            headers=headers,
        )
        try:
            with tls.urlopen(req, timeout=60) as r:
                meta = json.loads(r.read())
        except urllib.error.URLError as e:
            self._error(500, "InternalError", str(e))
            return
        self._reply(200, headers={"ETag": f'"{meta.get("etag", "")}"'})

    def _get_object(self, bucket, key, head: bool):
        entry = self.s3.filer.lookup(self.s3.object_path(bucket, key))
        if entry is None or entry.is_directory:
            if head:
                self._reply(404)
            else:
                self._error(404, "NoSuchKey", key)
            return
        # conditional requests (RFC 9110 semantics S3 clients cache with)
        from seaweedfs_tpu.filer.chunks import etag_of as _etag_of

        etag = _etag_of(entry.chunks, entry.attributes.md5)
        inm = self.headers.get("If-None-Match", "")
        if inm:
            # RFC 9110: when If-None-Match is present, If-Modified-Since
            # MUST be ignored — a failed ETag match means the client's copy
            # is stale even if the 1s-granular Last-Modified looks current
            if inm.strip('"') in (etag, "*"):
                self._reply(304, headers={"ETag": f'"{etag}"'})
                return
        else:
            ims = self.headers.get("If-Modified-Since", "")
            if ims:
                import email.utils as _eut

                try:
                    since = _eut.parsedate_to_datetime(ims).timestamp()
                    if int(entry.attributes.mtime) <= int(since):
                        self._reply(304, headers={"ETag": f'"{etag}"'})
                        return
                except (TypeError, ValueError):
                    pass  # unparseable date: ignore the condition
        fwd = {}
        rng = self.headers.get("Range", "")
        if rng and not head:
            fwd["Range"] = rng
        req = urllib.request.Request(
            self.s3.filer_url(self.s3.object_path(bucket, key)),
            headers=fwd,
            method="HEAD" if head else "GET",
        )
        try:
            with tls.urlopen(req, timeout=60) as r:
                body = b"" if head else r.read()
                out_headers = {
                    "ETag": r.headers.get("ETag", ""),
                    "Last-Modified": r.headers.get("Last-Modified", ""),
                    "Accept-Ranges": "bytes",
                }
                for k, v in r.headers.items():
                    if k.lower().startswith("x-amz-meta-"):
                        out_headers[k] = v
                tagging = r.headers.get(self.TAGS_KEY, "")
                if tagging:  # S3 exposes only the count, not the tags
                    out_headers["x-amz-tagging-count"] = str(
                        len(urllib.parse.parse_qsl(tagging, keep_blank_values=True))
                    )
                if r.headers.get("Content-Range"):
                    out_headers["Content-Range"] = r.headers["Content-Range"]
                if head:
                    out_headers["Content-Length"] = r.headers.get("Content-Length", "0")
                    self.send_response(r.status)
                    self.send_header(
                        "Content-Type", r.headers.get("Content-Type", "application/octet-stream")
                    )
                    for k, v in out_headers.items():
                        self.send_header(k, v)
                    self.end_headers()
                    return
                self._reply(
                    r.status,
                    body,
                    r.headers.get("Content-Type", "application/octet-stream"),
                    headers=out_headers,
                )
        except urllib.error.HTTPError as e:
            if e.code == 416:
                self._error(416, "InvalidRange")
            else:
                self._error(404, "NoSuchKey", key)

    def _resolve_copy_source(self, src: str, identity):
        """Shared x-amz-copy-source resolution for CopyObject and
        UploadPartCopy: parse, validate the path, check the caller's Read
        grant on the SOURCE bucket (the signature only proved Write on the
        destination), and confirm the source exists and is an object —
        a directory source would otherwise serve the filer's JSON listing
        as object bytes. Replies the error itself; returns
        (s_bucket, s_key) or None."""
        src = urllib.parse.unquote(src)
        if src.startswith("/"):
            src = src[1:]
        s_bucket, _, s_key = src.partition("/")
        if not s_key or not _valid_path(s_bucket, s_key):
            self._error(400, "InvalidArgument", "invalid copy source")
            return None
        if not identity.can_do(ACTION_READ, s_bucket):
            self._error(403, "AccessDenied", f"no Read on {s_bucket}")
            return None
        s_entry = self.s3.filer.lookup(self.s3.object_path(s_bucket, s_key))
        if s_entry is None or s_entry.is_directory:
            self._error(404, "NoSuchKey", src)
            return None
        return s_bucket, s_key

    def _copy_object(self, bucket, key, src, identity):
        resolved = self._resolve_copy_source(src, identity)
        if resolved is None:
            return
        s_bucket, s_key = resolved
        # stream through the filer: read source, write dest (fresh needles,
        # so source delete can never orphan the copy)
        try:
            with tls.urlopen(
                self.s3.filer_url(self.s3.object_path(s_bucket, s_key)), timeout=60
            ) as r:
                data = r.read()
                ctype = r.headers.get("Content-Type", "application/octet-stream")
        except urllib.error.URLError as e:
            self._error(500, "InternalError", str(e))
            return
        req = urllib.request.Request(
            self.s3.filer_url(self.s3.object_path(bucket, key)),
            data=data,
            method="PUT",
            headers={"Content-Type": ctype},
        )
        with tls.urlopen(req, timeout=60) as r:
            meta = json.loads(r.read())
        root = _xml("CopyObjectResult")
        _sub(root, "ETag", f'"{meta.get("etag", "")}"')
        _sub(root, "LastModified", _iso(time.time()))
        self._reply(200, _render(root))

    def _delete_object(self, bucket, key):
        try:
            self.s3.filer.delete(self.s3.object_path(bucket, key))
        except Exception:  # noqa: BLE001 — S3 delete is idempotent
            pass
        self._reply(204)

    # -- object tagging (Get/Put/DeleteObjectTagging) --------------------------
    #
    # Tags live in the entry's extended attributes under TAGS_KEY as the
    # same urlencoded k=v&k=v form the x-amz-tagging PUT header uses, so a
    # tagged upload and a PutObjectTagging produce identical state.

    TAGS_KEY = "x-amz-tagging"
    MAX_TAGS = 10  # AWS object-tagging limit

    def _lookup_object(self, bucket, key):
        entry = self.s3.filer.lookup(self.s3.object_path(bucket, key))
        if entry is None or entry.is_directory:
            self._error(404, "NoSuchKey", key)
            return None
        return entry

    def _entry_tags(self, entry) -> str:
        """The stored tag string, tolerant of HTTP header-name case (the
        filer keeps upload headers verbatim, e.g. 'X-amz-tagging')."""
        for k, v in entry.extended.items():
            if k.lower() == self.TAGS_KEY:
                return v
        return ""

    def _drop_entry_tags(self, entry) -> bool:
        victims = [k for k in entry.extended if k.lower() == self.TAGS_KEY]
        for k in victims:
            del entry.extended[k]
        return bool(victims)

    def _get_acl(self):
        """Canned private/FULL_CONTROL ACL (Get{Bucket,Object}Acl): access
        control here is identity-based (SigV4 + IAM actions), not ACLs, but
        SDK flows probe these endpoints and must not get a 4xx/501."""
        root = _xml("AccessControlPolicy")
        owner = _sub(root, "Owner")
        _sub(owner, "ID", "weedtpu")
        _sub(owner, "DisplayName", "weedtpu")
        grants = _sub(root, "AccessControlList")
        grant = _sub(grants, "Grant")
        grantee = _sub(grant, "Grantee")
        grantee.set("xmlns:xsi", "http://www.w3.org/2001/XMLSchema-instance")
        grantee.set("xsi:type", "CanonicalUser")
        _sub(grantee, "ID", "weedtpu")
        _sub(grant, "Permission", "FULL_CONTROL")
        self._reply(200, _render(root))

    def _get_tagging(self, bucket, key):
        entry = self._lookup_object(bucket, key)
        if entry is None:
            return
        root = _xml("Tagging")
        tagset = _sub(root, "TagSet")
        for k, v in urllib.parse.parse_qsl(
            self._entry_tags(entry), keep_blank_values=True
        ):
            t = _sub(tagset, "Tag")
            _sub(t, "Key", k)
            _sub(t, "Value", v)
        self._reply(200, _render(root))

    def _put_tagging(self, bucket, key, body):
        entry = self._lookup_object(bucket, key)
        if entry is None:
            return
        try:
            tree = ET.fromstring(body)
        except ET.ParseError:
            self._error(400, "MalformedXML")
            return
        ns = tree.tag[: tree.tag.index("}") + 1] if tree.tag.startswith("{") else ""
        tags: list[tuple[str, str]] = []
        for t in tree.findall(f"{ns}TagSet/{ns}Tag"):
            k_el, v_el = t.find(f"{ns}Key"), t.find(f"{ns}Value")
            k = (k_el.text or "") if k_el is not None else ""
            v = (v_el.text or "") if v_el is not None else ""
            if not k or len(k) > 128 or len(v) > 256:
                self._error(400, "InvalidTag", k)
                return
            tags.append((k, v))
        if len(tags) > self.MAX_TAGS:
            self._error(400, "BadRequest", f"up to {self.MAX_TAGS} tags allowed")
            return
        if len({k for k, _ in tags}) != len(tags):
            self._error(400, "InvalidTag", "duplicate tag keys")
            return
        self._drop_entry_tags(entry)
        entry.extended[self.TAGS_KEY] = urllib.parse.urlencode(tags)
        self.s3.filer.update(entry)
        self._reply(200)

    def _delete_tagging(self, bucket, key):
        entry = self._lookup_object(bucket, key)
        if entry is None:
            return
        if self._drop_entry_tags(entry):
            self.s3.filer.update(entry)
        self._reply(204)

    def _delete_objects(self, bucket, body):
        try:
            tree = ET.fromstring(body)
        except ET.ParseError:
            self._error(400, "MalformedXML")
            return
        ns = ""
        if tree.tag.startswith("{"):
            ns = tree.tag[: tree.tag.index("}") + 1]
        root = _xml("DeleteResult")
        for obj in tree.findall(f"{ns}Object"):
            key_el = obj.find(f"{ns}Key")
            if key_el is None or not key_el.text:
                continue
            if not _valid_path(bucket, key_el.text):
                err = _sub(root, "Error")
                _sub(err, "Key", key_el.text)
                _sub(err, "Code", "InvalidArgument")
                continue
            try:
                self.s3.filer.delete(self.s3.object_path(bucket, key_el.text))
            except Exception:  # noqa: BLE001
                pass
            d = _sub(root, "Deleted")
            _sub(d, "Key", key_el.text)
        self._reply(200, _render(root))

    # -- multipart ------------------------------------------------------------

    def _upload_dir(self, bucket, upload_id):
        return f"{UPLOADS_ROOT}/{bucket}/{upload_id}"

    def _valid_upload(self, upload_id) -> bool:
        """Reject any uploadId that is not a uuid4().hex we could have
        minted — 404 NoSuchUpload, same as an unknown id."""
        if _UPLOAD_ID_RE.fullmatch(upload_id or ""):
            return True
        self._error(404, "NoSuchUpload")
        return False

    def _initiate_multipart(self, bucket, key):
        from seaweedfs_tpu.filer.entry import Entry as _E

        upload_id = uuid.uuid4().hex
        meta = {
            "key": key,
            "content_type": self.headers.get("Content-Type", "application/octet-stream"),
            **{
                k.lower(): v
                for k, v in self.headers.items()
                if k.lower().startswith("x-amz-meta-")
            },
        }
        e = _E(path=self._upload_dir(bucket, upload_id), is_directory=True)
        e.extended = {"s3": json.dumps(meta)}
        self.s3.filer.create(e)
        root = _xml("InitiateMultipartUploadResult")
        _sub(root, "Bucket", bucket)
        _sub(root, "Key", key)
        _sub(root, "UploadId", upload_id)
        self._reply(200, _render(root))

    def _upload_part(self, bucket, key, q, body, identity):
        part = httpd.safe_int(q.get("partNumber"), -1)
        if not 1 <= part <= 10000:
            self._error(400, "InvalidArgument", "bad partNumber")
            return
        upload_id = q["uploadId"]
        if not self._valid_upload(upload_id):
            return
        if self.s3.filer.lookup(self._upload_dir(bucket, upload_id)) is None:
            self._error(404, "NoSuchUpload")
            return
        # UploadPartCopy: the part's bytes come from an existing object
        # (optionally a range) instead of the request body
        copy_src = self.headers.get("x-amz-copy-source", "")
        was_copy = bool(copy_src)
        src_resp = None
        put_headers: dict[str, str] = {}
        if was_copy:
            opened = self._open_copy_source(copy_src, identity)
            if opened is None:
                return  # error already replied
            # stream the source straight through to the staging path: parts
            # can be up to 5 GiB and buffering one in gateway memory is an
            # OOM (r4 advisor finding) — urllib takes a file-like body when
            # the length is pinned by an explicit Content-Length
            src_resp, length = opened
            body = src_resp
            put_headers["Content-Length"] = str(length)
        path = f"{self._upload_dir(bucket, upload_id)}/part{part:05d}"
        try:
            req = urllib.request.Request(
                self.s3.filer_url(path), data=body, headers=put_headers, method="PUT"
            )
            with tls.urlopen(req, timeout=600 if was_copy else 60) as r:
                meta = json.loads(r.read())
        finally:
            if src_resp is not None:
                src_resp.close()
        etag = meta.get("etag", "")
        if was_copy:  # CopyPartResult body, per the API shape
            root = _xml("CopyPartResult")
            _sub(root, "ETag", f'"{etag}"')
            _sub(root, "LastModified", _iso(time.time()))
            self._reply(200, _render(root), headers={"ETag": f'"{etag}"'})
        else:
            self._reply(200, headers={"ETag": f'"{etag}"'})

    def _open_copy_source(self, src: str, identity):
        """Resolve x-amz-copy-source [+ x-amz-copy-source-range] to an OPEN
        streaming response for UploadPartCopy (shared parse/auth/existence
        via _resolve_copy_source) -> (file-like, length). The caller owns
        closing it. Replies the error itself; None on failure."""
        resolved = self._resolve_copy_source(src, identity)
        if resolved is None:
            return None
        s_bucket, s_key = resolved
        headers = {}
        rng = self.headers.get("x-amz-copy-source-range", "")
        if rng:
            headers["Range"] = rng
        try:
            r = tls.urlopen(
                urllib.request.Request(
                    self.s3.filer_url(self.s3.object_path(s_bucket, s_key)),
                    headers=headers,
                ),
                timeout=600,
            )
            length = r.headers.get("Content-Length")
            if length is None:
                # a filer that doesn't pin the length forces a buffered
                # fallback — urllib needs Content-Length for file-like data
                buf = r.read()
                r.close()
                return io.BytesIO(buf), len(buf)
            return r, int(length)
        except urllib.error.HTTPError as e:
            if e.code == 416:
                self._error(416, "InvalidRange")
            elif e.code == 404:  # raced a delete since the lookup
                self._error(404, "NoSuchKey", src)
            else:  # a filer 5xx is OUR failure, not a missing source
                self._error(500, "InternalError", f"filer returned {e.code}")
            return None
        except urllib.error.URLError as e:
            self._error(500, "InternalError", str(e))
            return None

    def _list_parts(self, bucket, key, upload_id):
        if not self._valid_upload(upload_id):
            return
        d = self._upload_dir(bucket, upload_id)
        if self.s3.filer.lookup(d) is None:
            self._error(404, "NoSuchUpload")
            return
        root = _xml("ListPartsResult")
        _sub(root, "Bucket", bucket)
        _sub(root, "Key", key)
        _sub(root, "UploadId", upload_id)
        for e in self.s3.filer.list(d, limit=10000):
            num = httpd.safe_int(e.name[4:], -1) if e.name.startswith("part") else -1
            if num < 0:  # stray entry, not one of our staged parts
                continue
            p = _sub(root, "Part")
            _sub(p, "PartNumber", str(num))
            _sub(p, "ETag", f'"{e.attributes.md5}"')
            _sub(p, "Size", str(e.size))
            _sub(p, "LastModified", _iso(e.attributes.mtime))
        self._reply(200, _render(root))

    def _complete_multipart(self, bucket, key, upload_id, body):
        from seaweedfs_tpu.filer.entry import Attributes, Entry as _E, FileChunk

        if not self._valid_upload(upload_id):
            return
        d = self._upload_dir(bucket, upload_id)
        dir_entry = self.s3.filer.lookup(d)
        if dir_entry is None:
            self._error(404, "NoSuchUpload")
            return
        staged = {}
        for e in self.s3.filer.list(d, limit=10000):
            num = httpd.safe_int(e.name[4:], -1) if e.name.startswith("part") else -1
            if num >= 0:
                staged[num] = e
        # S3 commits exactly the parts the client lists, validating
        # ETags and ascending order — never just "everything staged"
        try:
            tree = ET.fromstring(body)
        except ET.ParseError:
            self._error(400, "MalformedXML")
            return
        ns = tree.tag[: tree.tag.index("}") + 1] if tree.tag.startswith("{") else ""
        req_parts: list[tuple[int, str]] = []
        for pe in tree.findall(f"{ns}Part"):
            num_el, etag_el = pe.find(f"{ns}PartNumber"), pe.find(f"{ns}ETag")
            num = httpd.safe_int(num_el.text if num_el is not None else None, -1)
            etag = (etag_el.text or "").strip().strip('"') if etag_el is not None else ""
            req_parts.append((num, etag))
        if not req_parts:
            self._error(400, "InvalidPart")
            return
        nums = [n for n, _ in req_parts]
        if nums != sorted(nums) or len(set(nums)) != len(nums):
            self._error(400, "InvalidPartOrder")
            return
        for num, etag in req_parts:
            e = staged.get(num)
            if e is None or (etag and etag != e.attributes.md5):
                self._error(400, "InvalidPart", f"part {num}")
                return
        parts = [staged[n] for n in nums]
        # splice part chunk lists; no data copy (filer_multipart.go pattern)
        chunks: list[FileChunk] = []
        offset = 0
        etag_md5 = hashlib.md5()
        for p in parts:
            for c in sorted(p.chunks, key=lambda c: c.offset):
                chunks.append(
                    FileChunk(
                        fid=c.fid,
                        offset=offset + c.offset,
                        size=c.size,
                        mtime_ns=c.mtime_ns,
                        etag=c.etag,
                        is_chunk_manifest=c.is_chunk_manifest,
                    )
                )
            offset += p.size
            etag_md5.update(bytes.fromhex(p.attributes.md5))
        meta = json.loads(dir_entry.extended.get("s3", "{}"))
        etag = f"{etag_md5.hexdigest()}-{len(parts)}"
        entry = _E(
            path=self.s3.object_path(bucket, key),
            attributes=Attributes(
                mtime=time.time(),
                mime=meta.get("content_type", "application/octet-stream"),
                md5=etag,
                file_size=offset,
            ),
            chunks=chunks,
            extended={k: v for k, v in meta.items() if k.startswith("x-amz-meta-")},
        )
        self.s3.filer.create(entry)
        # drop the staging entries but keep the needles (now owned by the
        # final object)
        self.s3.filer.delete(d, recursive=True, delete_data=False)
        root = _xml("CompleteMultipartUploadResult")
        _sub(root, "Location", f"{tls.scheme()}://{self.s3.url}/{bucket}/{key}")
        _sub(root, "Bucket", bucket)
        _sub(root, "Key", key)
        _sub(root, "ETag", f'"{etag}"')
        self._reply(200, _render(root))

    def _abort_multipart(self, bucket, key, upload_id):
        if not self._valid_upload(upload_id):
            return
        d = self._upload_dir(bucket, upload_id)
        if self.s3.filer.lookup(d) is not None:
            self.s3.filer.delete(d, recursive=True)
        self._reply(204)
