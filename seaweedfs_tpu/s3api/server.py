"""S3ApiServer — bucket/object/multipart REST handlers over the filer,
mirror of weed/s3api/s3api_server.go, s3api_bucket_handlers.go,
s3api_object_handlers.go, s3api_object_handlers_multipart.go,
filer_multipart.go [VERIFY: mount empty; SURVEY.md §2.1 "S3 gateway"].

Wire layout matches the reference: buckets are filer directories under
/buckets/<name>; multipart uploads stage parts under
/buckets/.uploads/<bucket>/<uploadId>/ and Complete splices the parts'
chunk lists into the final entry WITHOUT copying data (the reference
does the same chunk-list surgery in filer_multipart.go).

Data plane: proxied through the filer HTTP API (chunking to the volume
tier happens there). Metadata plane: filer RPC.

Supported: ListBuckets, Create/Delete/HeadBucket, ListObjectsV1/V2
(prefix, delimiter, marker/continuation, max-keys), Put/Get/Head/Delete
Object (+Range), CopyObject, DeleteObjects (bulk XML), multipart
lifecycle (initiate/uploadPart/complete/abort/listParts), SigV4 auth.

Listing order note: keys stream in directory-DFS order (names sorted per
directory), which differs from strict full-key lexicographic order only
when a sibling name extends a directory name with a byte < '/'.
"""

from __future__ import annotations

import hashlib
import io
import json
import re
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
import uuid
import xml.etree.ElementTree as ET
from typing import Iterator, Optional

from seaweedfs_tpu import stats
from seaweedfs_tpu.filer.client import FilerClient
from seaweedfs_tpu.filer.entry import Entry
from seaweedfs_tpu.s3api import policy as policy_mod
from seaweedfs_tpu.s3api.auth import (
    ACTION_LIST,
    ACTION_READ,
    ACTION_WRITE,
    ACTION_ADMIN,
    Iam,
    Identity,
    load_identities,
    save_identities,
)
from seaweedfs_tpu.utils import httpd
from seaweedfs_tpu.security import tls

BUCKETS_ROOT = "/buckets"
UPLOADS_ROOT = "/buckets/.uploads"
_XMLNS = "http://s3.amazonaws.com/doc/2006-03-01/"

# uploadIds are minted as uuid4().hex by _initiate_multipart; anything
# else in the query string is attacker-controlled path material (an
# unvalidated id containing '..' walks out of the staging area and can
# delete a victim bucket via AbortMultipartUpload)
_UPLOAD_ID_RE = re.compile(r"^[0-9a-f]{32}$")


VERSIONS_SUFFIX = ".s3versions"
# ids this gateway mints (hex time_ns + random) or AWS's pre-versioning
# "null" — anything else in ?versionId is attacker-controlled path
# material (a '..' would normalize out of the version archive and read or
# delete entries in other buckets)
_VERSION_ID_RE = re.compile(r"^(?:[0-9a-f]{24}|null)$")


def _valid_path(bucket: str, key: str) -> bool:
    """Reject bucket/key pairs whose filer path would normalize outside
    /buckets/<bucket>/ — '.'/'..'/empty segments and dot-prefixed bucket
    names (which would collide with the .uploads staging area). Segments
    ending in the reserved .s3versions suffix are refused on every
    surface: they are the per-key version archives."""
    if bucket.startswith("."):
        return False
    segs = key.split("/") if key else []
    if any(s in ("", ".", "..") for s in segs[:-1]):
        return False
    if any(s.endswith(VERSIONS_SUFFIX) for s in segs):
        return False
    # a single trailing "" segment is a folder-marker key ("a/b/")
    return not (segs and segs[-1] in (".", ".."))


class S3ApiServer:
    def __init__(
        self,
        filer_http_address: str,
        filer_grpc_address: str,
        port: int = 0,
        host: str = "127.0.0.1",
        iam: Optional[Iam] = None,
        extra_hosts: Optional[set[str]] = None,
    ):
        self.filer_http = filer_http_address
        self.filer = FilerClient(filer_grpc_address)
        self.iam = iam or Iam()
        # additional advertised host:port names (LB/proxy fronts) accepted
        # as the signed `host` header besides this server's own url;
        # pre-lowercased here so the per-request compare is a set lookup
        self.extra_hosts = {h.lower() for h in (extra_hosts or ())}
        self._iam_checked_at = 0.0
        self._policy_cache: dict[str, tuple[float, Optional[dict]]] = {}
        self._versioning_cache: dict[str, tuple[float, str]] = {}
        self.host = host
        self._http = _ThreadingHTTPServer((host, port), _Handler)
        tls.maybe_wrap_https(self._http)  # data-path HTTPS when configured
        self._http.s3_server = self
        self.port = self._http.server_address[1]
        self.extra_hosts |= {f"{h}:{self.port}" for h in httpd.loopback_aliases(host)}
        self._thread = threading.Thread(target=self._http.serve_forever, daemon=True)

    @property
    def url(self) -> str:
        return f"{self.host}:{self.port}"

    def start(self) -> None:
        # ensure the buckets root exists
        from seaweedfs_tpu.filer.entry import Entry as _E

        if self.filer.lookup(BUCKETS_ROOT) is None:
            self.filer.create(_E(path=BUCKETS_ROOT, is_directory=True))
        # seed the filer KV (the cluster-wide identity root the IAM API
        # serves) with the file-configured identities: otherwise the IAM
        # API sees an empty KV, stays in its open bootstrap window, and
        # an unauthenticated caller can mint an admin this gateway would
        # honor on its next KV reload
        if not self.iam.open:
            existing = load_identities(self.filer)
            if existing is None or not any(
                i.access_key for i in existing.identities
            ):
                save_identities(self.filer, self.iam)
        self._thread.start()

    def stop(self) -> None:
        self._http.shutdown()
        self._http.server_close()
        self.filer.close()

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()

    # -- bucket policies ------------------------------------------------------

    POLICY_KEY = "s3_policy"
    _POLICY_TTL = 5.0  # s; policies are read per request, entries are not
    _CACHE_MAX = 4096  # hard cap on cached bucket names (real buckets only)
    #: guards every structural mutation of _policy_cache/_versioning_cache:
    #: the HTTP server is threaded, and an unlocked eviction scan racing a
    #: concurrent insert/pop would raise 'dict changed size during iteration'
    _cache_lock = threading.Lock()

    @classmethod
    def _cache_put(cls, cache: dict, bucket: str, value, now: float) -> None:
        """Bounded insert shared by the policy and versioning caches: evict
        every expired entry first (the TTL previously only gated reuse, so
        dead entries lived forever), then cap the size — a flood past the
        cap resets the cache rather than growing it (entries rebuild on
        demand at one filer lookup each)."""
        with cls._cache_lock:
            for k in [k for k, v in cache.items() if v[0] <= now]:
                cache.pop(k, None)
            if len(cache) >= cls._CACHE_MAX:
                cache.clear()
            cache[bucket] = (now + cls._POLICY_TTL, value)

    @classmethod
    def _cache_drop(cls, cache: dict, bucket: str) -> None:
        with cls._cache_lock:
            cache.pop(bucket, None)

    def get_bucket_policy(self, bucket: str) -> Optional[dict]:
        """The bucket's policy document, or None — cached briefly so the
        per-request evaluation doesn't pay a filer lookup per call.
        Nonexistent buckets are NOT cached: unauthenticated probes naming
        random buckets must not grow server state."""
        now = time.monotonic()
        cached = self._policy_cache.get(bucket)
        if cached is not None and cached[0] > now:
            return cached[1]
        entry = self.filer.lookup(self.bucket_path(bucket))
        if entry is None:
            self._cache_drop(self._policy_cache, bucket)
            return None
        doc = None
        raw = entry.extended.get(self.POLICY_KEY)
        if raw:
            try:
                doc = json.loads(raw)
            except ValueError:
                doc = None  # unreadable stored policy must not 500 reads
        self._cache_put(self._policy_cache, bucket, doc, now)
        return doc

    def put_bucket_policy(self, bucket: str, doc: dict) -> bool:
        entry = self.filer.lookup(self.bucket_path(bucket))
        if entry is None or not entry.is_directory:
            return False
        entry.extended[self.POLICY_KEY] = json.dumps(doc)
        self.filer.update(entry)
        self._cache_drop(self._policy_cache, bucket)
        return True

    def delete_bucket_policy(self, bucket: str) -> bool:
        entry = self.filer.lookup(self.bucket_path(bucket))
        if entry is None or not entry.is_directory:
            return False
        if self.POLICY_KEY in entry.extended:
            del entry.extended[self.POLICY_KEY]
            self.filer.update(entry)
        self._cache_drop(self._policy_cache, bucket)
        return True

    # -- object versioning ----------------------------------------------------
    #
    # Layout ([ref: weed/s3api versioning — mount empty]; reference keeps a
    # hidden .versions folder per key): the PLAIN path always holds the
    # latest real version; every older version — and every delete marker —
    # lives in a sibling directory `<key>.s3versions/` keyed by version id.
    # A marker as "latest" therefore shows as: plain path absent, marker
    # entry newest in the archive. Version ids are zero-padded hex
    # time_ns + random, so lexical order is creation order.

    VERSIONING_KEY = "s3_versioning"
    MARKER_KEY = "s3_delete_marker"
    VID_KEY = "x-amz-version-id"

    def get_bucket_versioning(self, bucket: str) -> str:
        """'' | 'Enabled' | 'Suspended' (briefly cached like policies;
        nonexistent buckets are not cached, matching get_bucket_policy)."""
        now = time.monotonic()
        cached = self._versioning_cache.get(bucket)
        if cached is not None and cached[0] > now:
            return cached[1]
        entry = self.filer.lookup(self.bucket_path(bucket))
        if entry is None:
            self._cache_drop(self._versioning_cache, bucket)
            return ""
        status = entry.extended.get(self.VERSIONING_KEY, "")
        self._cache_put(self._versioning_cache, bucket, status, now)
        return status

    def set_bucket_versioning(self, bucket: str, status: str) -> bool:
        entry = self.filer.lookup(self.bucket_path(bucket))
        if entry is None or not entry.is_directory:
            return False
        entry.extended[self.VERSIONING_KEY] = status
        self.filer.update(entry)
        self._cache_drop(self._versioning_cache, bucket)
        return True

    def versions_dir(self, bucket: str, key: str) -> str:
        return self.object_path(bucket, key) + VERSIONS_SUFFIX

    @staticmethod
    def new_version_id() -> str:
        return f"{time.time_ns():016x}{uuid.uuid4().hex[:8]}"

    # -- filer helpers --------------------------------------------------------

    def bucket_path(self, bucket: str) -> str:
        return f"{BUCKETS_ROOT}/{bucket}"

    def object_path(self, bucket: str, key: str) -> str:
        return f"{BUCKETS_ROOT}/{bucket}/{key}"

    def filer_url(self, path: str, query: str = "") -> str:
        enc = urllib.parse.quote(path)
        return f"{tls.scheme()}://{self.filer_http}{enc}" + (f"?{query}" if query else "")

    def walk_keys(
        self, bucket: str, prefix: str = "", after: str = ""
    ) -> Iterator[Entry]:
        """Yield file entries under the bucket whose key starts with
        `prefix` and sorts after `after`, in directory-DFS order. The
        `after` marker is pushed down into per-directory listings so a
        paginated walk costs O(depth × page), not a full re-walk."""
        root = self.bucket_path(bucket)

        def rec(dir_path: str, base: str) -> Iterator[Entry]:
            # base = key prefix of this directory ("" at the bucket root,
            # else "a/b/"). Resume the listing at the marker's component.
            start, include = "", False
            if after and after.startswith(base) and len(after) > len(base):
                start = after[len(base) :].split("/", 1)[0]
                include = True
            while True:
                batch = self.filer.list(
                    dir_path, start_from=start, include_start=include, limit=256
                )
                include = False
                if not batch:
                    return
                for e in batch:
                    key = e.path[len(root) + 1 :]
                    if e.is_directory and e.name.endswith(VERSIONS_SUFFIX):
                        continue  # per-key version archives are not keys
                    if e.is_directory:
                        probe = key + "/"
                        if after and after > probe and not after.startswith(probe):
                            continue  # whole subtree sorts before the marker
                        # descend only where the subtree can match prefix
                        if probe.startswith(prefix) or prefix.startswith(probe):
                            yield from rec(e.path, probe)
                    elif key.startswith(prefix) and (not after or key > after):
                        yield e
                start = batch[-1].name

        yield from rec(root, "")


# -- HTTP --------------------------------------------------------------------


class _ThreadingHTTPServer(httpd.ThreadingHTTPServer):
    s3_server: "S3ApiServer"


def _xml(tag: str, ns: bool = True) -> ET.Element:
    e = ET.Element(tag)
    if ns:
        e.set("xmlns", _XMLNS)
    return e


def _sub(parent: ET.Element, tag: str, text: Optional[str] = None) -> ET.Element:
    e = ET.SubElement(parent, tag)
    if text is not None:
        e.text = text
    return e


def _render(root: ET.Element) -> bytes:
    return b'<?xml version="1.0" encoding="UTF-8"?>\n' + ET.tostring(root)


def _iso(ts: float) -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%S.000Z", time.gmtime(ts))


class _Handler(httpd.QuietHandler):
    @property
    def s3(self) -> S3ApiServer:
        return self.server.s3_server

    # -- plumbing -------------------------------------------------------------

    def _parse(self) -> Optional[tuple[str, str, dict]]:
        """Parse /bucket/key?query. Returns None (after replying 400) for
        paths with '.'/'..'/empty segments — the filer normalizes paths,
        so an un-rejected '..' would let a bucket-scoped identity escape
        its bucket (the reference validates object names the same way)."""
        u = urllib.parse.urlparse(self.path)
        parts = urllib.parse.unquote(u.path).lstrip("/").split("/", 1)
        bucket = parts[0]
        key = parts[1] if len(parts) > 1 else ""
        if not _valid_path(bucket, key):
            self._error(400, "InvalidArgument", "invalid bucket or object name")
            return None
        q = {k: v[0] for k, v in urllib.parse.parse_qs(u.query, keep_blank_values=True).items()}
        return bucket, key, q

    def _body(self) -> Optional[bytes]:
        body = self.read_body()
        if body is None:
            self.reply_length_required()
        return body

    def _reply(self, code: int, body: bytes = b"", ctype="application/xml", headers=None):
        self.send_reply(code, body, ctype, headers=headers)

    def _error(self, code: int, s3_code: str, message: str = ""):
        root = _xml("Error", ns=False)
        _sub(root, "Code", s3_code)
        _sub(root, "Message", message or s3_code)
        self._reply(code, _render(root))

    def _s3_action_name(self, action: str, key: str, query: str) -> str:
        """Map this request's coarse action to the s3:* name bucket
        policies speak. Admin (bucket-management) operations return "" —
        they stay identity-only, which keeps Get/Put/DeleteBucketPolicy
        out of the policy's own reach (no AWS-style deny-yourself
        lockout). Bucket-level reads approximate to s3:ListBucket.

        Version-granular requests authorize under the separate
        s3:*Version action names, like AWS: a public-read policy granting
        s3:GetObject must NOT expose historical versions via ?versionId,
        and s3:DeleteObject must not permit permanent versionId deletes
        (nor may a Deny written against the *Version names silently never
        match)."""
        # FIRST-value-wins, exactly like _parse builds the q the handlers
        # serve from — authorization and serving must agree on which
        # versionId a request names, or a duplicated query key smuggles a
        # versioned read/delete past the base-action policy check
        q = {
            k: v[0]
            for k, v in urllib.parse.parse_qs(query, keep_blank_values=True).items()
        }
        # ?versions is a presence-flagged subresource, but versionId only
        # selects a version when its VALUE is non-empty — the handlers
        # treat a blank ?versionId= as "current object", so the action
        # name must agree or a base-action Deny would be bypassed
        versioned = bool(q.get("versionId", "").strip())
        if action == ACTION_LIST:
            return "s3:ListBucketVersions" if "versions" in q else "s3:ListBucket"
        if action == ACTION_READ:
            if not key:
                return "s3:ListBucketVersions" if "versions" in q else "s3:ListBucket"
            return "s3:GetObjectVersion" if versioned else "s3:GetObject"
        if action == ACTION_WRITE:
            if self.command == "DELETE" or (self.command == "POST" and "delete" in q):
                return (
                    "s3:DeleteObjectVersion" if versioned else "s3:DeleteObject"
                )
            return "s3:PutObject"
        return ""

    @staticmethod
    def _is_anonymous(identity) -> bool:
        return not identity.access_key and identity.name == "anonymous"

    def _policy_verdict(self, bucket, key, identity, s3_action):
        """Evaluate the bucket's policy for one (identity, action,
        resource): False = explicit deny, True = allow, None = no
        statement matched (or no policy)."""
        pol = self.s3.get_bucket_policy(bucket)
        if pol is None:
            return None
        resource = policy_mod.ARN_PREFIX + (f"{bucket}/{key}" if key else bucket)
        return policy_mod.evaluate(
            pol,
            identity_name=identity.name,
            access_key=identity.access_key,
            anonymous=self._is_anonymous(identity),
            action=s3_action,
            resource=resource,
        )

    def _auth(self, action: str, bucket: str, payload: bytes):
        """Authenticate + authorize; returns the resolved Identity (truthy)
        or None after replying 403/501 — callers needing a second
        authorization check (CopyObject's source-bucket Read) reuse the
        identity instead of re-verifying the signature.

        Authorization order (IAM semantics): bucket policy explicit Deny
        -> refuse, policy Allow -> grant (this is how anonymous access to
        a public-read bucket works), else identity grants."""
        u = urllib.parse.urlparse(self.path)
        headers = {k.lower(): v for k, v in self.headers.items()}
        path = urllib.parse.unquote(u.path) or "/"
        expect_hosts = {self.s3.url.lower()} | self.s3.extra_hosts
        if self.s3.iam.open:
            # an open gateway must notice identities minted via the IAM
            # API and start enforcing auth (throttled KV poll)
            now = time.monotonic()
            if now - self.s3._iam_checked_at > 5.0:
                self.s3._iam_checked_at = now
                fresh = load_identities(self.s3.filer)
                if fresh is not None and fresh.identities:
                    self.s3.iam.identities = fresh.identities
        identity, err = self.s3.iam.authenticate(
            self.command, path, u.query, headers, payload,
            expect_service="s3", expect_hosts=expect_hosts,
        )
        if identity is None and err == "NotImplemented":
            self._error(501, "NotImplemented", "aws-chunked (STREAMING-*) uploads not supported")
            return None
        if identity is None and err == "InvalidAccessKeyId":
            # the IAM API may have minted new credentials since start:
            # reload the persisted identity set once and retry
            fresh = load_identities(self.s3.filer)
            if fresh is not None and fresh.identities:
                self.s3.iam.identities = fresh.identities
                identity, err = self.s3.iam.authenticate(
                    self.command, path, u.query, headers, payload,
                    expect_service="s3", expect_hosts=expect_hosts,
                )
        anonymous = False
        if (
            identity is None
            and "authorization" not in headers
            and "X-Amz-Signature=" not in u.query
        ):
            # truly unsigned request (no auth material at all): not an auth
            # failure yet — a bucket policy may grant the anonymous
            # principal (public-read buckets). A SIGNED request missing a
            # required header keeps its original 403.
            identity = Identity("anonymous", "", "", [])
            anonymous = True
        if identity is None:
            self._error(403, err)
            return None
        # derive the object key from the path: policy resources are
        # key-granular while callers authorize at bucket granularity
        parts = path.lstrip("/").split("/", 1)
        req_key = parts[1] if len(parts) > 1 else ""
        s3_act = self._s3_action_name(action, req_key, u.query)
        if bucket and s3_act:
            verdict = self._policy_verdict(bucket, req_key, identity, s3_act)
            if verdict is False:
                self._error(403, "AccessDenied", "denied by bucket policy")
                return None
            if verdict is True:
                return identity
        if anonymous:
            self._error(403, "AccessDenied", "anonymous access not granted")
            return None
        if not identity.can_do(action, bucket):
            self._error(403, "AccessDenied", f"no {action} on {bucket}")
            return None
        return identity

    # -- dispatch -------------------------------------------------------------

    def do_GET(self):
        parsed = self._parse()
        if parsed is None:
            return
        bucket, key, q = parsed
        if not bucket:
            stats.S3RequestCounter.labels("ListBuckets").inc()
            if self._auth(ACTION_LIST, "", b""):
                self._list_buckets()
            return
        if not key:
            if "uploadId" in q:
                self._error(404, "NoSuchUpload")
                return
            if "location" in q:
                stats.S3RequestCounter.labels("GetBucketLocation").inc()
                if self._auth(ACTION_READ, bucket, b""):
                    if self.s3.filer.lookup(self.s3.bucket_path(bucket)) is None:
                        self._error(404, "NoSuchBucket")
                    else:  # single-region deployment: the us-east-1 form
                        self._reply(200, _render(_xml("LocationConstraint")))
                return
            if "acl" in q:
                stats.S3RequestCounter.labels("GetBucketAcl").inc()
                if self._auth(ACTION_READ, bucket, b""):
                    if self.s3.filer.lookup(self.s3.bucket_path(bucket)) is None:
                        self._error(404, "NoSuchBucket")
                    else:
                        self._get_acl()
                return
            if "policy" in q:
                stats.S3RequestCounter.labels("GetBucketPolicy").inc()
                if self._auth(ACTION_ADMIN, bucket, b""):
                    if self.s3.filer.lookup(self.s3.bucket_path(bucket)) is None:
                        self._error(404, "NoSuchBucket")
                    else:
                        pol = self.s3.get_bucket_policy(bucket)
                        if pol is None:
                            self._error(
                                404, "NoSuchBucketPolicy",
                                "the bucket policy does not exist",
                            )
                        else:
                            self._reply(
                                200, json.dumps(pol).encode(),
                                ctype="application/json",
                            )
                return
            if "versioning" in q:
                stats.S3RequestCounter.labels("GetBucketVersioning").inc()
                if self._auth(ACTION_READ, bucket, b""):
                    if self.s3.filer.lookup(self.s3.bucket_path(bucket)) is None:
                        self._error(404, "NoSuchBucket")
                    else:
                        root = _xml("VersioningConfiguration")
                        status = self.s3.get_bucket_versioning(bucket)
                        if status:
                            _sub(root, "Status", status)
                        self._reply(200, _render(root))
                return
            if "versions" in q:
                stats.S3RequestCounter.labels("ListObjectVersions").inc()
                if self._auth(ACTION_LIST, bucket, b""):
                    self._list_object_versions(bucket, q)
                return
            stats.S3RequestCounter.labels("ListObjects").inc()
            if self._auth(ACTION_LIST, bucket, b""):
                self._list_objects(bucket, q)
            return
        if "uploadId" in q:
            stats.S3RequestCounter.labels("ListParts").inc()
            if self._auth(ACTION_READ, bucket, b""):
                self._list_parts(bucket, key, q["uploadId"])
            return
        if "tagging" in q:
            stats.S3RequestCounter.labels("GetObjectTagging").inc()
            if self._auth(ACTION_READ, bucket, b""):
                self._get_tagging(bucket, key)
            return
        if "acl" in q:
            stats.S3RequestCounter.labels("GetObjectAcl").inc()
            if self._auth(ACTION_READ, bucket, b""):
                if self._lookup_object(bucket, key) is not None:
                    self._get_acl()
            return
        stats.S3RequestCounter.labels("GetObject").inc()
        if self._auth(ACTION_READ, bucket, b""):
            self._get_object(bucket, key, head=False, version_id=q.get("versionId", ""))

    def do_HEAD(self):
        parsed = self._parse()
        if parsed is None:
            return
        bucket, key, q = parsed
        if not key:
            if self._auth(ACTION_READ, bucket, b""):
                if self.s3.filer.lookup(self.s3.bucket_path(bucket)) is None:
                    self._reply(404)
                else:
                    self._reply(200)
            return
        if self._auth(ACTION_READ, bucket, b""):
            self._get_object(bucket, key, head=True, version_id=q.get("versionId", ""))

    def do_PUT(self):
        parsed = self._parse()
        if parsed is None:
            return
        bucket, key, q = parsed
        body = self._body()
        if body is None:
            return
        if "acl" in q:
            # PutBucketAcl/PutObjectAcl: accepted and ignored — access
            # control is identity-based here; SDKs setting canned ACLs
            # must not fail their whole upload flow on a 501. Existence is
            # still checked so a failed-upload + put_object_acl sequence
            # 404s like AWS instead of reporting false success.
            stats.S3RequestCounter.labels("PutAcl").inc()
            if self._auth(ACTION_WRITE, bucket, body):
                if self.s3.filer.lookup(self.s3.bucket_path(bucket)) is None:
                    self._error(404, "NoSuchBucket")
                elif key and self.s3.filer.lookup(
                    self.s3.object_path(bucket, key)
                ) is None:
                    self._error(404, "NoSuchKey", key)
                else:
                    self._reply(200)
            return
        if not key and "versioning" in q:
            stats.S3RequestCounter.labels("PutBucketVersioning").inc()
            if self._auth(ACTION_ADMIN, bucket, body):
                try:
                    tree = ET.fromstring(body)
                except ET.ParseError:
                    self._error(400, "MalformedXML")
                    return
                ns = tree.tag[: tree.tag.index("}") + 1] if tree.tag.startswith("{") else ""
                el = tree.find(f"{ns}Status")
                status = (el.text or "").strip() if el is not None else ""
                if status not in ("Enabled", "Suspended"):
                    self._error(400, "MalformedXML", "Status must be Enabled|Suspended")
                    return
                if not self.s3.set_bucket_versioning(bucket, status):
                    self._error(404, "NoSuchBucket")
                else:
                    self._reply(200)
            return
        if not key and "policy" in q:
            stats.S3RequestCounter.labels("PutBucketPolicy").inc()
            if self._auth(ACTION_ADMIN, bucket, body):
                try:
                    doc = policy_mod.parse_policy(body, bucket)
                except policy_mod.PolicyError as e:
                    self._error(400, "MalformedPolicy", str(e))
                    return
                if not self.s3.put_bucket_policy(bucket, doc):
                    self._error(404, "NoSuchBucket")
                else:
                    self._reply(204)
            return
        if not key:
            stats.S3RequestCounter.labels("CreateBucket").inc()
            if self._auth(ACTION_ADMIN, bucket, body):
                self._create_bucket(bucket)
            return
        if "partNumber" in q and "uploadId" in q:
            stats.S3RequestCounter.labels("UploadPart").inc()
            identity = self._auth(ACTION_WRITE, bucket, body)
            if identity:
                self._upload_part(bucket, key, q, body, identity)
            return
        if "tagging" in q:
            stats.S3RequestCounter.labels("PutObjectTagging").inc()
            if self._auth(ACTION_WRITE, bucket, body):
                self._put_tagging(bucket, key, body)
            return
        stats.S3RequestCounter.labels("PutObject").inc()
        identity = self._auth(ACTION_WRITE, bucket, body)
        if identity is None:
            return
        src = self.headers.get("x-amz-copy-source", "")
        if src:
            self._copy_object(bucket, key, src, identity)
        else:
            self._put_object(bucket, key, body)

    def do_POST(self):
        parsed = self._parse()
        if parsed is None:
            return
        bucket, key, q = parsed
        body = self._body()
        if body is None:
            return
        if not key and "delete" in q:
            stats.S3RequestCounter.labels("DeleteObjects").inc()
            identity = self._auth(ACTION_WRITE, bucket, body)
            if identity:
                self._delete_objects(bucket, body, identity)
            return
        if key and "uploads" in q:
            stats.S3RequestCounter.labels("CreateMultipartUpload").inc()
            if self._auth(ACTION_WRITE, bucket, body):
                self._initiate_multipart(bucket, key)
            return
        if key and "uploadId" in q:
            stats.S3RequestCounter.labels("CompleteMultipartUpload").inc()
            if self._auth(ACTION_WRITE, bucket, body):
                self._complete_multipart(bucket, key, q["uploadId"], body)
            return
        self._error(400, "InvalidRequest")

    def do_DELETE(self):
        parsed = self._parse()
        if parsed is None:
            return
        bucket, key, q = parsed
        if not key and "policy" in q:
            stats.S3RequestCounter.labels("DeleteBucketPolicy").inc()
            if self._auth(ACTION_ADMIN, bucket, b""):
                if not self.s3.delete_bucket_policy(bucket):
                    self._error(404, "NoSuchBucket")
                else:
                    self._reply(204)
            return
        if not key:
            stats.S3RequestCounter.labels("DeleteBucket").inc()
            if self._auth(ACTION_ADMIN, bucket, b""):
                self._delete_bucket(bucket)
            return
        if "uploadId" in q:
            stats.S3RequestCounter.labels("AbortMultipartUpload").inc()
            if self._auth(ACTION_WRITE, bucket, b""):
                self._abort_multipart(bucket, key, q["uploadId"])
            return
        if "tagging" in q:
            stats.S3RequestCounter.labels("DeleteObjectTagging").inc()
            if self._auth(ACTION_WRITE, bucket, b""):
                self._delete_tagging(bucket, key)
            return
        stats.S3RequestCounter.labels("DeleteObject").inc()
        if self._auth(ACTION_WRITE, bucket, b""):
            self._delete_object(bucket, key, q.get("versionId", ""))

    # -- buckets --------------------------------------------------------------

    def _list_buckets(self):
        root = _xml("ListAllMyBucketsResult")
        owner = _sub(root, "Owner")
        _sub(owner, "ID", "weedtpu")
        buckets = _sub(root, "Buckets")
        for e in self.s3.filer.list(BUCKETS_ROOT, limit=10000):
            if not e.is_directory or e.name.startswith("."):
                continue
            b = _sub(buckets, "Bucket")
            _sub(b, "Name", e.name)
            _sub(b, "CreationDate", _iso(e.attributes.crtime))
        self._reply(200, _render(root))

    def _create_bucket(self, bucket):
        from seaweedfs_tpu.filer.entry import Entry as _E

        if self.s3.filer.lookup(self.s3.bucket_path(bucket)) is not None:
            self._error(409, "BucketAlreadyExists")
            return
        self.s3.filer.create(_E(path=self.s3.bucket_path(bucket), is_directory=True))
        self._reply(200, headers={"Location": f"/{bucket}"})

    def _delete_bucket(self, bucket):
        path = self.s3.bucket_path(bucket)
        if self.s3.filer.lookup(path) is None:
            self._error(404, "NoSuchBucket")
            return
        if self.s3.filer.list(path, limit=1):
            self._error(409, "BucketNotEmpty")
            return
        self.s3.filer.delete(path, recursive=True)
        # a same-named bucket created within the cache TTL must not
        # inherit the dead bucket's policy or versioning state
        self.s3._cache_drop(self.s3._policy_cache, bucket)
        self.s3._cache_drop(self.s3._versioning_cache, bucket)
        try:
            # in-flight multipart staging references needles in this
            # bucket's collection; dropping the collection without it
            # would leave staged entries pointing at dead volumes
            self.s3.filer.delete(f"{UPLOADS_ROOT}/{bucket}", recursive=True)
        except Exception:  # noqa: BLE001 — no staged uploads
            pass
        try:
            # per-bucket collections: drop the bucket's volumes so the
            # space (incl. tombstoned needles) comes back immediately
            self.s3.filer.delete_collection(bucket)
        except Exception:  # noqa: BLE001 — reclamation is best-effort;
            pass  # auto-vacuum collects stragglers later
        self._reply(204)

    # -- listing --------------------------------------------------------------

    def _list_objects(self, bucket, q):
        if self.s3.filer.lookup(self.s3.bucket_path(bucket)) is None:
            self._error(404, "NoSuchBucket")
            return
        v2 = q.get("list-type") == "2"
        prefix = q.get("prefix", "")
        delimiter = q.get("delimiter", "")
        max_keys = httpd.safe_int(q.get("max-keys"), 1000)
        after = q.get("start-after", "") or q.get("marker", "")
        token = q.get("continuation-token", "")
        if token:
            after = token

        contents: list[Entry] = []
        common: list[str] = []
        seen_common = set()
        truncated = False
        next_after = ""
        # a continuation token can point INSIDE a prefix group already
        # emitted on the previous page — skip the rest of that group or
        # the CommonPrefix would repeat across pages
        skip_group = ""
        if after and delimiter and after.startswith(prefix):
            rest = after[len(prefix) :]
            d = rest.find(delimiter)
            if d >= 0:
                skip_group = prefix + rest[: d + len(delimiter)]
        for e in self.s3.walk_keys(bucket, prefix, after=after):
            key = e.path[len(self.s3.bucket_path(bucket)) + 1 :]
            if skip_group and key.startswith(skip_group):
                continue
            if delimiter:
                rest = key[len(prefix) :]
                d = rest.find(delimiter)
                if d >= 0:
                    cp = prefix + rest[: d + len(delimiter)]
                    if cp not in seen_common:
                        if len(contents) + len(seen_common) >= max_keys:
                            truncated = True
                            break
                        seen_common.add(cp)
                        common.append(cp)
                        next_after = key
                    continue
            if len(contents) + len(seen_common) >= max_keys:
                truncated = True
                break
            contents.append(e)
            next_after = key

        root = _xml("ListBucketResult")
        _sub(root, "Name", bucket)
        _sub(root, "Prefix", prefix)
        _sub(root, "MaxKeys", str(max_keys))
        _sub(root, "IsTruncated", "true" if truncated else "false")
        if delimiter:
            _sub(root, "Delimiter", delimiter)
        if v2:
            _sub(root, "KeyCount", str(len(contents) + len(common)))
            if truncated:
                _sub(root, "NextContinuationToken", next_after)
        elif truncated:
            _sub(root, "NextMarker", next_after)
        for e in contents:
            key = e.path[len(self.s3.bucket_path(bucket)) + 1 :]
            c = _sub(root, "Contents")
            _sub(c, "Key", key)
            _sub(c, "LastModified", _iso(e.attributes.mtime))
            _sub(c, "ETag", f'"{e.attributes.md5 or ""}"')
            _sub(c, "Size", str(e.size))
            _sub(c, "StorageClass", "STANDARD")
        for cp in common:
            p = _sub(root, "CommonPrefixes")
            _sub(p, "Prefix", cp)
        self._reply(200, _render(root))

    def _walk_version_rows(self, bucket, prefix):
        """Yield (key, [(vid, is_marker, entry)] newest-first) in key order
        per directory — both live keys AND keys whose only remains are
        archived versions/markers (those have no plain entry, so
        walk_keys alone would never surface them)."""
        root = self.s3.bucket_path(bucket)

        def rec(dir_path, base):
            per_key: dict[str, dict] = {}
            subdirs: dict[str, object] = {}
            start = ""
            while True:
                batch = self.s3.filer.list(dir_path, start_from=start, limit=256)
                if not batch:
                    break
                for e in batch:
                    if e.is_directory and e.name.endswith(VERSIONS_SUFFIX):
                        per_key.setdefault(
                            base + e.name[: -len(VERSIONS_SUFFIX)], {}
                        )["vdir"] = e
                    elif e.is_directory:
                        subdirs[base + e.name + "/"] = e
                    else:
                        per_key.setdefault(base + e.name, {})["plain"] = e
                start = batch[-1].name
                if len(batch) < 256:
                    break
            for name in sorted(set(per_key) | set(subdirs)):
                if name in subdirs:
                    if name.startswith(prefix) or prefix.startswith(name):
                        yield from rec(subdirs[name].path, name)
                    continue
                if not name.startswith(prefix):
                    continue
                recs = []
                plain = per_key[name].get("plain")
                if plain is not None:
                    recs.append((self._entry_vid(plain), False, plain))
                if "vdir" in per_key[name]:
                    # the shared newest-first (and paginated) archive walk
                    recs.extend(self._archived_records(per_key[name]["vdir"].path))
                if recs:
                    yield name, recs

        yield from rec(root, "")

    def _list_object_versions(self, bucket, q):
        """ListObjectVersions: every version and delete marker, newest
        first per key. Honors prefix, max-keys, and key-marker; truncation
        cuts at KEY boundaries and names NextKeyMarker, so SDK paginators
        make progress (version-id-marker sub-pagination is not
        implemented — a single key's versions always ship whole)."""
        if self.s3.filer.lookup(self.s3.bucket_path(bucket)) is None:
            self._error(404, "NoSuchBucket")
            return
        prefix = q.get("prefix", "")
        max_keys = httpd.safe_int(q.get("max-keys"), 1000)
        key_marker = q.get("key-marker", "")
        root = _xml("ListVersionsResult")
        _sub(root, "Name", bucket)
        _sub(root, "Prefix", prefix)
        _sub(root, "MaxKeys", str(max_keys))
        if key_marker:
            _sub(root, "KeyMarker", key_marker)
        emitted = 0
        truncated = False
        last_key = ""
        for key, recs in self._walk_version_rows(bucket, prefix):
            if key_marker and key <= key_marker:
                continue
            if emitted and emitted + len(recs) > max_keys:
                truncated = True
                break
            for i, (vid, is_marker, entry) in enumerate(recs):
                el = _sub(root, "DeleteMarker" if is_marker else "Version")
                _sub(el, "Key", key)
                _sub(el, "VersionId", vid)
                _sub(el, "IsLatest", "true" if i == 0 else "false")
                _sub(el, "LastModified", _iso(entry.attributes.mtime))
                if not is_marker:
                    _sub(el, "ETag", f'"{entry.attributes.md5 or ""}"')
                    _sub(el, "Size", str(entry.size))
                    _sub(el, "StorageClass", "STANDARD")
                emitted += 1
            last_key = key
            if emitted >= max_keys:
                # stop scanning; whether anything follows decides truncation
                truncated = True
                break
        if truncated and last_key:
            _sub(root, "NextKeyMarker", last_key)
        _sub(root, "IsTruncated", "true" if truncated else "false")
        self._reply(200, _render(root))

    # -- objects --------------------------------------------------------------

    def _put_object(self, bucket, key, body):
        if self.s3.filer.lookup(self.s3.bucket_path(bucket)) is None:
            self._error(404, "NoSuchBucket")
            return
        headers = {
            "Content-Type": self.headers.get("Content-Type", "application/octet-stream")
        }
        for k, v in self.headers.items():
            if k.lower().startswith("x-amz-meta-"):
                headers[k] = v
        tagging = self.headers.get(self.TAGS_KEY, "")
        if tagging:
            pairs = urllib.parse.parse_qsl(tagging, keep_blank_values=True)
            if len(pairs) > self.MAX_TAGS:
                self._error(400, "BadRequest", f"up to {self.MAX_TAGS} tags allowed")
                return
            headers[self.TAGS_KEY] = tagging  # filer stores x-amz-* in extended
        meta: dict = {}

        def write(filer_path, vid_headers):
            req = urllib.request.Request(
                self.s3.filer_url(filer_path),
                data=body,
                method="PUT",
                headers={**headers, **vid_headers},  # x-amz-* land in extended
            )
            with tls.urlopen(req, timeout=60) as r:
                meta.update(json.loads(r.read()))

        try:
            vid_headers = self._versioned_commit(bucket, key, write)
        except urllib.error.URLError as e:
            self._error(500, "InternalError", str(e))
            return
        self._reply(
            200, headers={"ETag": f'"{meta.get("etag", "")}"', **vid_headers}
        )

    def _get_object(self, bucket, key, head: bool, version_id: str = ""):
        if version_id and not _VERSION_ID_RE.fullmatch(version_id):
            self._reply(400) if head else self._error(
                400, "InvalidArgument", "invalid versionId"
            )
            return
        if version_id:
            filer_path, entry = self._locate_version(bucket, key, version_id)
            if entry is None or entry.is_directory:
                self._reply(404) if head else self._error(
                    404, "NoSuchVersion", version_id
                )
                return
            if self._is_marker(entry):
                # AWS: GET on a delete-marker version is 405
                self._reply(
                    405, headers={"x-amz-delete-marker": "true", "Allow": "DELETE"}
                ) if head else self._error(405, "MethodNotAllowed", "delete marker")
                return
        else:
            filer_path = self.s3.object_path(bucket, key)
            entry = self.s3.filer.lookup(filer_path)
        if entry is None or entry.is_directory:
            marker_headers = {}
            if self.s3.get_bucket_versioning(bucket):
                # latest may be a delete marker: 404, but say so
                versions = self._key_versions(bucket, key)
                if versions and versions[0][1]:
                    marker_headers = {
                        "x-amz-delete-marker": "true",
                        self.s3.VID_KEY: versions[0][0],
                    }
            if head:
                self._reply(404, headers=marker_headers)
            else:
                root = _xml("Error", ns=False)
                _sub(root, "Code", "NoSuchKey")
                _sub(root, "Message", key)
                self._reply(404, _render(root), headers=marker_headers)
            return
        # conditional requests (RFC 9110 semantics S3 clients cache with)
        from seaweedfs_tpu.filer.chunks import etag_of as _etag_of

        etag = _etag_of(entry.chunks, entry.attributes.md5)
        inm = self.headers.get("If-None-Match", "")
        if inm:
            # RFC 9110: when If-None-Match is present, If-Modified-Since
            # MUST be ignored — a failed ETag match means the client's copy
            # is stale even if the 1s-granular Last-Modified looks current
            if inm.strip('"') in (etag, "*"):
                self._reply(304, headers={"ETag": f'"{etag}"'})
                return
        else:
            ims = self.headers.get("If-Modified-Since", "")
            if ims:
                import email.utils as _eut

                try:
                    since = _eut.parsedate_to_datetime(ims).timestamp()
                    if int(entry.attributes.mtime) <= int(since):
                        self._reply(304, headers={"ETag": f'"{etag}"'})
                        return
                except (TypeError, ValueError):
                    pass  # unparseable date: ignore the condition
        fwd = {}
        rng = self.headers.get("Range", "")
        if rng and not head:
            fwd["Range"] = rng
        req = urllib.request.Request(
            self.s3.filer_url(filer_path),
            headers=fwd,
            method="HEAD" if head else "GET",
        )
        try:
            with tls.urlopen(req, timeout=60) as r:
                body = b"" if head else r.read()
                out_headers = {
                    "ETag": r.headers.get("ETag", ""),
                    "Last-Modified": r.headers.get("Last-Modified", ""),
                    "Accept-Ranges": "bytes",
                }
                for k, v in r.headers.items():
                    if k.lower().startswith("x-amz-meta-") or (
                        k.lower() == self.s3.VID_KEY
                    ):
                        out_headers[k] = v
                tagging = r.headers.get(self.TAGS_KEY, "")
                if tagging:  # S3 exposes only the count, not the tags
                    out_headers["x-amz-tagging-count"] = str(
                        len(urllib.parse.parse_qsl(tagging, keep_blank_values=True))
                    )
                if r.headers.get("Content-Range"):
                    out_headers["Content-Range"] = r.headers["Content-Range"]
                if head:
                    out_headers["Content-Length"] = r.headers.get("Content-Length", "0")
                    self.send_response(r.status)
                    self.send_header(
                        "Content-Type", r.headers.get("Content-Type", "application/octet-stream")
                    )
                    for k, v in out_headers.items():
                        self.send_header(k, v)
                    self.end_headers()
                    return
                self._reply(
                    r.status,
                    body,
                    r.headers.get("Content-Type", "application/octet-stream"),
                    headers=out_headers,
                )
        except urllib.error.HTTPError as e:
            if e.code == 416:
                self._error(416, "InvalidRange")
            else:
                self._error(404, "NoSuchKey", key)

    def _resolve_copy_source(self, src: str, identity):
        """Shared x-amz-copy-source resolution for CopyObject and
        UploadPartCopy: parse, validate the path, check the caller's Read
        grant on the SOURCE bucket (the signature only proved Write on the
        destination), and confirm the source exists and is an object —
        a directory source would otherwise serve the filer's JSON listing
        as object bytes. Replies the error itself; returns
        (s_bucket, s_key, s_filer_path, version_id) or None."""
        # AWS appends ?versionId AFTER the percent-encoded key, so split
        # BEFORE unquoting — decoding first would truncate a key that
        # legitimately contains an encoded '?' (%3F)
        src_enc, _, src_q = src.partition("?")
        src_path = urllib.parse.unquote(src_enc)
        if src_path.startswith("/"):
            src_path = src_path[1:]
        version_id = ""
        if src_q:
            qd = dict(urllib.parse.parse_qsl(src_q, keep_blank_values=True))
            version_id = qd.get("versionId", "")
        s_bucket, _, s_key = src_path.partition("/")
        if not s_key or not _valid_path(s_bucket, s_key):
            self._error(400, "InvalidArgument", "invalid copy source")
            return None
        if version_id and not _VERSION_ID_RE.fullmatch(version_id):
            self._error(400, "InvalidArgument", "invalid copy source versionId")
            return None
        # the SOURCE bucket's policy binds here too: a denied direct GET
        # must not be readable by copying it into a bucket the caller can
        # write ([ref: weed/s3api — mount empty]; IAM evaluation order).
        # A versioned source reads under s3:GetObjectVersion, like AWS.
        verdict = self._policy_verdict(
            s_bucket, s_key, identity,
            "s3:GetObjectVersion" if version_id else "s3:GetObject",
        )
        if verdict is False:
            self._error(403, "AccessDenied", "denied by source bucket policy")
            return None
        if verdict is not True and not identity.can_do(ACTION_READ, s_bucket):
            self._error(403, "AccessDenied", f"no Read on {s_bucket}")
            return None
        if version_id:
            s_path, s_entry = self._locate_version(s_bucket, s_key, version_id)
            if s_entry is not None and self._is_marker(s_entry):
                # AWS: a copy source may not name a delete marker by id
                self._error(400, "InvalidRequest", "source version is a delete marker")
                return None
        else:
            s_path = self.s3.object_path(s_bucket, s_key)
            s_entry = self.s3.filer.lookup(s_path)
        if s_entry is None or s_entry.is_directory:
            self._error(
                404, "NoSuchVersion" if version_id else "NoSuchKey", src
            )
            return None
        return s_bucket, s_key, s_path, version_id

    def _copy_object(self, bucket, key, src, identity):
        resolved = self._resolve_copy_source(src, identity)
        if resolved is None:
            return
        _s_bucket, _s_key, s_path, src_vid = resolved
        # stream through the filer: read source, write dest (fresh needles,
        # so source delete can never orphan the copy)
        try:
            with tls.urlopen(self.s3.filer_url(s_path), timeout=60) as r:
                data = r.read()
                ctype = r.headers.get("Content-Type", "application/octet-stream")
        except urllib.error.URLError as e:
            self._error(500, "InternalError", str(e))
            return
        meta: dict = {}

        def write(filer_path, vid_headers):
            req = urllib.request.Request(
                self.s3.filer_url(filer_path),
                data=data,
                method="PUT",
                headers={"Content-Type": ctype, **vid_headers},
            )
            with tls.urlopen(req, timeout=60) as r:
                meta.update(json.loads(r.read()))

        vid_headers = self._versioned_commit(bucket, key, write)
        if src_vid:
            vid_headers = {**vid_headers, "x-amz-copy-source-version-id": src_vid}
        root = _xml("CopyObjectResult")
        _sub(root, "ETag", f'"{meta.get("etag", "")}"')
        _sub(root, "LastModified", _iso(time.time()))
        self._reply(200, _render(root), headers=vid_headers)

    # -- versioning plumbing ---------------------------------------------------

    def _entry_vid(self, entry) -> str:
        """The stored version id of an entry; 'null' for objects written
        while versioning was off/suspended (AWS's pre-versioning id)."""
        for k, v in entry.extended.items():
            if k.lower() == self.s3.VID_KEY:
                return v
        return "null"

    def _is_marker(self, entry) -> bool:
        return self.s3.MARKER_KEY in entry.extended

    def _locate_version(self, bucket, key, version_id):
        """-> (filer_path, entry|None) for one version id: the plain path
        when the current latest carries that id, else the archive slot —
        the ONE resolution shared by GET, DELETE, and copy-source (a
        caller-local copy of this branch would drift on marker/latest
        semantics)."""
        plain = self.s3.object_path(bucket, key)
        cur = self.s3.filer.lookup(plain)
        if (
            cur is not None
            and not cur.is_directory
            and self._entry_vid(cur) == version_id
        ):
            return plain, cur
        vpath = f"{self.s3.versions_dir(bucket, key)}/{version_id}"
        return vpath, self.s3.filer.lookup(vpath)

    def _archive_current(self, bucket, key, status, drop_null: bool = False) -> None:
        """Move the plain-path entry (the latest version) into the version
        archive under its own id, clearing the way for a new latest.
        Under Suspended, the 'null' version is overwritten in place (AWS
        semantics), so only real-id versions are archived — unless
        drop_null asks for the delete-path behavior, where the null
        version is permanently removed."""
        plain = self.s3.object_path(bucket, key)
        cur = self.s3.filer.lookup(plain)
        if cur is None or cur.is_directory:
            return
        vid = self._entry_vid(cur)
        if status == "Suspended" and vid == "null":
            if drop_null:
                self.s3.filer.delete(plain)
            return
        self.s3.filer.rename(plain, f"{self.s3.versions_dir(bucket, key)}/{vid}")

    def _versioned_commit(self, bucket, key, write_fn) -> dict[str, str]:
        """Orchestrate any write that replaces the plain path (PutObject,
        CopyObject, CompleteMultipartUpload). write_fn(filer_path,
        vid_headers) performs the actual write at the path it is given.

        Versioned buckets stage the new object INSIDE the archive first,
        then move the old latest aside, then rename the staged write into
        place — so a failed write leaves the previous latest untouched
        instead of already-archived (a plain-path-first ordering would
        turn a 500 into a 404 for readers). Returns the version headers
        the caller's reply must carry."""
        status = self.s3.get_bucket_versioning(bucket)
        plain = self.s3.object_path(bucket, key)
        if status not in ("Enabled", "Suspended"):
            write_fn(plain, {})
            return {}
        vid = self.s3.new_version_id() if status == "Enabled" else "null"
        vid_headers = {self.s3.VID_KEY: vid}
        staging = f"{self.s3.versions_dir(bucket, key)}/{vid}"
        write_fn(staging, vid_headers)
        self._archive_current(bucket, key, status)
        self.s3.filer.rename(staging, plain)
        return vid_headers

    #: filer page size for version-archive listings (class attr so tests
    #: can shrink it to exercise pagination without 1000+ versions)
    _VERSION_PAGE = 1000

    def _archived_records(self, vdir_path) -> list[tuple[str, bool, object]]:
        """[(vid, is_marker, entry)] of the version archive, newest first —
        the ONE ordering shared by listings, promotion, and marker
        detection (ties break on the time-ordered hex id). Paginated: a
        one-shot limited list would silently drop the NEWEST versions of a
        key with more versions than the limit (ids are time-ordered and
        the filer lists ascending), letting _promote_newest resurrect a
        stale version after a delete."""
        archived = []
        start = ""
        while True:
            batch = self.s3.filer.list(
                vdir_path, start_from=start, limit=self._VERSION_PAGE
            )
            if not batch:
                break
            archived.extend(e for e in batch if not e.is_directory)
            start = batch[-1].name
            if len(batch) < self._VERSION_PAGE:
                break
        archived.sort(key=lambda e: (e.attributes.mtime, e.name), reverse=True)
        return [(e.name, self._is_marker(e), e) for e in archived]

    def _key_versions(self, bucket, key) -> list[tuple[str, bool, object]]:
        """[(vid, is_marker, entry)] newest first. The plain entry (when
        present) is always the newest real version by the layout
        invariant; archived entries order by mtime."""
        out = []
        plain = self.s3.filer.lookup(self.s3.object_path(bucket, key))
        if plain is not None and not plain.is_directory:
            out.append((self._entry_vid(plain), False, plain))
        out.extend(self._archived_records(self.s3.versions_dir(bucket, key)))
        return out

    def _promote_newest(self, bucket, key) -> None:
        """After the current latest was permanently deleted: if the newest
        archived record is a REAL version, rename it back to the plain
        path so reads keep working (a marker stays archived — the key
        reads as deleted)."""
        vdir = self.s3.versions_dir(bucket, key)
        records = self._archived_records(vdir)
        if records and not records[0][1]:
            self.s3.filer.rename(
                f"{vdir}/{records[0][0]}", self.s3.object_path(bucket, key)
            )

    def _delete_object_versioned(self, bucket, key, version_id: str) -> dict:
        """Shared by DeleteObject and DeleteObjects. Returns the reply
        headers (version id / delete-marker) — S3 deletes are idempotent,
        so missing things succeed quietly."""
        from seaweedfs_tpu.filer.entry import Attributes as _A
        from seaweedfs_tpu.filer.entry import Entry as _E

        status = self.s3.get_bucket_versioning(bucket)
        plain = self.s3.object_path(bucket, key)
        if version_id and not _VERSION_ID_RE.fullmatch(version_id):
            raise ValueError("invalid versionId")
        if version_id:
            # permanent delete of one version
            vpath, ventry = self._locate_version(bucket, key, version_id)
            if vpath == plain:
                self.s3.filer.delete(plain)
                self._promote_newest(bucket, key)
                self._prune_versioned_remains(bucket, key)
                return {self.s3.VID_KEY: version_id}
            headers = {self.s3.VID_KEY: version_id}
            if ventry is not None:
                if self._is_marker(ventry):
                    headers["x-amz-delete-marker"] = "true"
                self.s3.filer.delete(vpath)
                if self._is_marker(ventry) and self.s3.filer.lookup(plain) is None:
                    # removing the masking marker can re-expose a version
                    self._promote_newest(bucket, key)
            self._prune_versioned_remains(bucket, key)
            return headers
        if status in ("Enabled", "Suspended"):
            # logical delete: archive the latest, leave a marker. Under
            # Suspended the 'null' version is REMOVED (AWS: the null
            # marker replaces it) — archiving-by-overwrite alone would
            # leave the plain path serving the supposedly deleted bytes.
            self._archive_current(bucket, key, status, drop_null=True)
            vid = self.s3.new_version_id() if status == "Enabled" else "null"
            marker = _E(
                path=f"{self.s3.versions_dir(bucket, key)}/{vid}",
                attributes=_A(mtime=time.time()),
                extended={self.s3.VID_KEY: vid, self.s3.MARKER_KEY: "1"},
            )
            self.s3.filer.create(marker)  # replaces a prior 'null' marker
            return {self.s3.VID_KEY: vid, "x-amz-delete-marker": "true"}
        try:
            self.s3.filer.delete(plain)
        except Exception:  # noqa: BLE001 — S3 delete is idempotent
            pass
        self._prune_empty_parents(bucket, key)
        return {}

    def _prune_versioned_remains(self, bucket, key) -> None:
        """After a permanent version delete: when the last version of a
        key is gone (plain path absent, archive empty), drop the empty
        archive dir and the folder husks — otherwise DeleteBucket on a
        fully-emptied versioned bucket reports BucketNotEmpty forever."""
        if self.s3.filer.lookup(self.s3.object_path(bucket, key)) is not None:
            return
        vdir = self.s3.versions_dir(bucket, key)
        try:
            if self.s3.filer.lookup(vdir) is not None:
                if self.s3.filer.list(vdir, limit=1):
                    return  # versions remain: the key still exists
                self.s3.filer.delete(vdir)
        except Exception:  # noqa: BLE001 — raced; husks are best-effort
            return
        self._prune_empty_parents(bucket, key)

    def _prune_empty_parents(self, bucket, key) -> None:
        """Remove now-empty ancestor DIRECTORIES of a deleted key, up to
        (never including) the bucket root — S3 has no real folders, and
        leaving husks behind blocks DeleteBucket's emptiness check
        ([ref: weed/s3api doDeleteEmptyDirectories — mount empty])."""
        # a folder-marker key ("a/b/") normalizes to the directory itself:
        # its first ancestor is a/  — probing the just-deleted path would
        # abort the walk on NOT_FOUND
        parts = key.rstrip("/").split("/")[:-1]
        while parts:
            d = self.s3.object_path(bucket, "/".join(parts))
            try:
                if self.s3.filer.list(d, limit=1):
                    return  # first non-empty ancestor ends the walk
                self.s3.filer.delete(d)
            except Exception:  # noqa: BLE001 — raced or already gone
                return
            parts.pop()

    def _delete_object(self, bucket, key, version_id: str = ""):
        try:
            headers = self._delete_object_versioned(bucket, key, version_id)
        except ValueError:
            self._error(400, "InvalidArgument", "invalid versionId")
            return
        self._reply(204, headers=headers)

    # -- object tagging (Get/Put/DeleteObjectTagging) --------------------------
    #
    # Tags live in the entry's extended attributes under TAGS_KEY as the
    # same urlencoded k=v&k=v form the x-amz-tagging PUT header uses, so a
    # tagged upload and a PutObjectTagging produce identical state.

    TAGS_KEY = "x-amz-tagging"
    MAX_TAGS = 10  # AWS object-tagging limit

    def _lookup_object(self, bucket, key):
        entry = self.s3.filer.lookup(self.s3.object_path(bucket, key))
        if entry is None or entry.is_directory:
            self._error(404, "NoSuchKey", key)
            return None
        return entry

    def _entry_tags(self, entry) -> str:
        """The stored tag string, tolerant of HTTP header-name case (the
        filer keeps upload headers verbatim, e.g. 'X-amz-tagging')."""
        for k, v in entry.extended.items():
            if k.lower() == self.TAGS_KEY:
                return v
        return ""

    def _drop_entry_tags(self, entry) -> bool:
        victims = [k for k in entry.extended if k.lower() == self.TAGS_KEY]
        for k in victims:
            del entry.extended[k]
        return bool(victims)

    def _get_acl(self):
        """Canned private/FULL_CONTROL ACL (Get{Bucket,Object}Acl): access
        control here is identity-based (SigV4 + IAM actions), not ACLs, but
        SDK flows probe these endpoints and must not get a 4xx/501."""
        root = _xml("AccessControlPolicy")
        owner = _sub(root, "Owner")
        _sub(owner, "ID", "weedtpu")
        _sub(owner, "DisplayName", "weedtpu")
        grants = _sub(root, "AccessControlList")
        grant = _sub(grants, "Grant")
        grantee = _sub(grant, "Grantee")
        grantee.set("xmlns:xsi", "http://www.w3.org/2001/XMLSchema-instance")
        grantee.set("xsi:type", "CanonicalUser")
        _sub(grantee, "ID", "weedtpu")
        _sub(grant, "Permission", "FULL_CONTROL")
        self._reply(200, _render(root))

    def _get_tagging(self, bucket, key):
        entry = self._lookup_object(bucket, key)
        if entry is None:
            return
        root = _xml("Tagging")
        tagset = _sub(root, "TagSet")
        for k, v in urllib.parse.parse_qsl(
            self._entry_tags(entry), keep_blank_values=True
        ):
            t = _sub(tagset, "Tag")
            _sub(t, "Key", k)
            _sub(t, "Value", v)
        self._reply(200, _render(root))

    def _put_tagging(self, bucket, key, body):
        entry = self._lookup_object(bucket, key)
        if entry is None:
            return
        try:
            tree = ET.fromstring(body)
        except ET.ParseError:
            self._error(400, "MalformedXML")
            return
        ns = tree.tag[: tree.tag.index("}") + 1] if tree.tag.startswith("{") else ""
        tags: list[tuple[str, str]] = []
        for t in tree.findall(f"{ns}TagSet/{ns}Tag"):
            k_el, v_el = t.find(f"{ns}Key"), t.find(f"{ns}Value")
            k = (k_el.text or "") if k_el is not None else ""
            v = (v_el.text or "") if v_el is not None else ""
            if not k or len(k) > 128 or len(v) > 256:
                self._error(400, "InvalidTag", k)
                return
            tags.append((k, v))
        if len(tags) > self.MAX_TAGS:
            self._error(400, "BadRequest", f"up to {self.MAX_TAGS} tags allowed")
            return
        if len({k for k, _ in tags}) != len(tags):
            self._error(400, "InvalidTag", "duplicate tag keys")
            return
        self._drop_entry_tags(entry)
        entry.extended[self.TAGS_KEY] = urllib.parse.urlencode(tags)
        self.s3.filer.update(entry)
        self._reply(200)

    def _delete_tagging(self, bucket, key):
        entry = self._lookup_object(bucket, key)
        if entry is None:
            return
        if self._drop_entry_tags(entry):
            self.s3.filer.update(entry)
        self._reply(204)

    def _delete_objects(self, bucket, body, identity):
        try:
            tree = ET.fromstring(body)
        except ET.ParseError:
            self._error(400, "MalformedXML")
            return
        ns = ""
        if tree.tag.startswith("{"):
            ns = tree.tag[: tree.tag.index("}") + 1]
        root = _xml("DeleteResult")
        for obj in tree.findall(f"{ns}Object"):
            key_el = obj.find(f"{ns}Key")
            if key_el is None or not key_el.text:
                continue
            if not _valid_path(bucket, key_el.text):
                err = _sub(root, "Error")
                _sub(err, "Key", key_el.text)
                _sub(err, "Code", "InvalidArgument")
                continue
            vid_el = obj.find(f"{ns}VersionId")
            vid = (vid_el.text or "").strip() if vid_el is not None else ""
            # the bucket-level _auth saw resource arn:...:bucket; per-key
            # denies (s3:DeleteObject on a prefix) must still bind here —
            # and an entry naming a VersionId is a permanent versioned
            # delete, which authorizes under s3:DeleteObjectVersion
            verdict = self._policy_verdict(
                bucket, key_el.text, identity,
                "s3:DeleteObjectVersion" if vid else "s3:DeleteObject",
            )
            if verdict is False or (
                self._is_anonymous(identity) and verdict is not True
            ):
                err = _sub(root, "Error")
                _sub(err, "Key", key_el.text)
                _sub(err, "Code", "AccessDenied")
                continue
            try:
                headers = self._delete_object_versioned(bucket, key_el.text, vid)
            except ValueError:
                err = _sub(root, "Error")
                _sub(err, "Key", key_el.text)
                _sub(err, "Code", "InvalidArgument")
                continue
            except Exception:  # noqa: BLE001
                headers = {}
            d = _sub(root, "Deleted")
            _sub(d, "Key", key_el.text)
            if headers.get("x-amz-delete-marker"):
                _sub(d, "DeleteMarker", "true")
                _sub(d, "DeleteMarkerVersionId", headers.get(self.s3.VID_KEY, ""))
            elif vid:
                _sub(d, "VersionId", vid)
        self._reply(200, _render(root))

    # -- multipart ------------------------------------------------------------

    def _upload_dir(self, bucket, upload_id):
        return f"{UPLOADS_ROOT}/{bucket}/{upload_id}"

    def _valid_upload(self, upload_id) -> bool:
        """Reject any uploadId that is not a uuid4().hex we could have
        minted — 404 NoSuchUpload, same as an unknown id."""
        if _UPLOAD_ID_RE.fullmatch(upload_id or ""):
            return True
        self._error(404, "NoSuchUpload")
        return False

    def _initiate_multipart(self, bucket, key):
        from seaweedfs_tpu.filer.entry import Entry as _E

        upload_id = uuid.uuid4().hex
        meta = {
            "key": key,
            "content_type": self.headers.get("Content-Type", "application/octet-stream"),
            **{
                k.lower(): v
                for k, v in self.headers.items()
                if k.lower().startswith("x-amz-meta-")
            },
        }
        e = _E(path=self._upload_dir(bucket, upload_id), is_directory=True)
        e.extended = {"s3": json.dumps(meta)}
        self.s3.filer.create(e)
        root = _xml("InitiateMultipartUploadResult")
        _sub(root, "Bucket", bucket)
        _sub(root, "Key", key)
        _sub(root, "UploadId", upload_id)
        self._reply(200, _render(root))

    def _upload_part(self, bucket, key, q, body, identity):
        part = httpd.safe_int(q.get("partNumber"), -1)
        if not 1 <= part <= 10000:
            self._error(400, "InvalidArgument", "bad partNumber")
            return
        upload_id = q["uploadId"]
        if not self._valid_upload(upload_id):
            return
        if self.s3.filer.lookup(self._upload_dir(bucket, upload_id)) is None:
            self._error(404, "NoSuchUpload")
            return
        # UploadPartCopy: the part's bytes come from an existing object
        # (optionally a range) instead of the request body
        copy_src = self.headers.get("x-amz-copy-source", "")
        was_copy = bool(copy_src)
        src_resp = None
        put_headers: dict[str, str] = {}
        if was_copy:
            opened = self._open_copy_source(copy_src, identity)
            if opened is None:
                return  # error already replied
            # stream the source straight through to the staging path: parts
            # can be up to 5 GiB and buffering one in gateway memory is an
            # OOM (r4 advisor finding) — urllib takes a file-like body when
            # the length is pinned by an explicit Content-Length
            src_resp, length, src_vid = opened
            body = src_resp
            put_headers["Content-Length"] = str(length)
        path = f"{self._upload_dir(bucket, upload_id)}/part{part:05d}"
        try:
            req = urllib.request.Request(
                self.s3.filer_url(path), data=body, headers=put_headers, method="PUT"
            )
            with tls.urlopen(req, timeout=600 if was_copy else 60) as r:
                meta = json.loads(r.read())
        finally:
            if src_resp is not None:
                src_resp.close()
        etag = meta.get("etag", "")
        if was_copy:  # CopyPartResult body, per the API shape
            root = _xml("CopyPartResult")
            _sub(root, "ETag", f'"{etag}"')
            _sub(root, "LastModified", _iso(time.time()))
            out_h = {"ETag": f'"{etag}"'}
            if src_vid:
                out_h["x-amz-copy-source-version-id"] = src_vid
            self._reply(200, _render(root), headers=out_h)
        else:
            self._reply(200, headers={"ETag": f'"{etag}"'})

    def _open_copy_source(self, src: str, identity):
        """Resolve x-amz-copy-source [+ x-amz-copy-source-range] to an OPEN
        streaming response for UploadPartCopy (shared parse/auth/existence
        via _resolve_copy_source) -> (file-like, length, source version
        id). The caller owns closing it. Replies the error itself; None
        on failure."""
        resolved = self._resolve_copy_source(src, identity)
        if resolved is None:
            return None
        _s_bucket, _s_key, s_path, _src_vid = resolved
        headers = {}
        rng = self.headers.get("x-amz-copy-source-range", "")
        if rng:
            headers["Range"] = rng
        try:
            r = tls.urlopen(
                urllib.request.Request(
                    self.s3.filer_url(s_path),
                    headers=headers,
                ),
                timeout=600,
            )
            length = r.headers.get("Content-Length")
            if length is None:
                # a filer that doesn't pin the length forces a buffered
                # fallback — urllib needs Content-Length for file-like data
                buf = r.read()
                r.close()
                return io.BytesIO(buf), len(buf), _src_vid
            return r, int(length), _src_vid
        except urllib.error.HTTPError as e:
            if e.code == 416:
                self._error(416, "InvalidRange")
            elif e.code == 404:  # raced a delete since the lookup
                self._error(404, "NoSuchKey", src)
            else:  # a filer 5xx is OUR failure, not a missing source
                self._error(500, "InternalError", f"filer returned {e.code}")
            return None
        except urllib.error.URLError as e:
            self._error(500, "InternalError", str(e))
            return None

    def _list_parts(self, bucket, key, upload_id):
        if not self._valid_upload(upload_id):
            return
        d = self._upload_dir(bucket, upload_id)
        if self.s3.filer.lookup(d) is None:
            self._error(404, "NoSuchUpload")
            return
        root = _xml("ListPartsResult")
        _sub(root, "Bucket", bucket)
        _sub(root, "Key", key)
        _sub(root, "UploadId", upload_id)
        for e in self.s3.filer.list(d, limit=10000):
            num = httpd.safe_int(e.name[4:], -1) if e.name.startswith("part") else -1
            if num < 0:  # stray entry, not one of our staged parts
                continue
            p = _sub(root, "Part")
            _sub(p, "PartNumber", str(num))
            _sub(p, "ETag", f'"{e.attributes.md5}"')
            _sub(p, "Size", str(e.size))
            _sub(p, "LastModified", _iso(e.attributes.mtime))
        self._reply(200, _render(root))

    def _complete_multipart(self, bucket, key, upload_id, body):
        from seaweedfs_tpu.filer.entry import Attributes, Entry as _E, FileChunk

        if not self._valid_upload(upload_id):
            return
        d = self._upload_dir(bucket, upload_id)
        dir_entry = self.s3.filer.lookup(d)
        if dir_entry is None:
            self._error(404, "NoSuchUpload")
            return
        staged = {}
        for e in self.s3.filer.list(d, limit=10000):
            num = httpd.safe_int(e.name[4:], -1) if e.name.startswith("part") else -1
            if num >= 0:
                staged[num] = e
        # S3 commits exactly the parts the client lists, validating
        # ETags and ascending order — never just "everything staged"
        try:
            tree = ET.fromstring(body)
        except ET.ParseError:
            self._error(400, "MalformedXML")
            return
        ns = tree.tag[: tree.tag.index("}") + 1] if tree.tag.startswith("{") else ""
        req_parts: list[tuple[int, str]] = []
        for pe in tree.findall(f"{ns}Part"):
            num_el, etag_el = pe.find(f"{ns}PartNumber"), pe.find(f"{ns}ETag")
            num = httpd.safe_int(num_el.text if num_el is not None else None, -1)
            etag = (etag_el.text or "").strip().strip('"') if etag_el is not None else ""
            req_parts.append((num, etag))
        if not req_parts:
            self._error(400, "InvalidPart")
            return
        nums = [n for n, _ in req_parts]
        if nums != sorted(nums) or len(set(nums)) != len(nums):
            self._error(400, "InvalidPartOrder")
            return
        for num, etag in req_parts:
            e = staged.get(num)
            if e is None or (etag and etag != e.attributes.md5):
                self._error(400, "InvalidPart", f"part {num}")
                return
        parts = [staged[n] for n in nums]
        # splice part chunk lists; no data copy (filer_multipart.go pattern)
        chunks: list[FileChunk] = []
        offset = 0
        etag_md5 = hashlib.md5()
        for p in parts:
            for c in sorted(p.chunks, key=lambda c: c.offset):
                chunks.append(
                    FileChunk(
                        fid=c.fid,
                        offset=offset + c.offset,
                        size=c.size,
                        mtime_ns=c.mtime_ns,
                        etag=c.etag,
                        is_chunk_manifest=c.is_chunk_manifest,
                    )
                )
            offset += p.size
            etag_md5.update(bytes.fromhex(p.attributes.md5))
        meta = json.loads(dir_entry.extended.get("s3", "{}"))
        etag = f"{etag_md5.hexdigest()}-{len(parts)}"

        def write(filer_path, vid_headers):
            self.s3.filer.create(
                _E(
                    path=filer_path,
                    attributes=Attributes(
                        mtime=time.time(),
                        mime=meta.get("content_type", "application/octet-stream"),
                        md5=etag,
                        file_size=offset,
                    ),
                    chunks=chunks,
                    extended={
                        **{
                            k: v
                            for k, v in meta.items()
                            if k.startswith("x-amz-meta-")
                        },
                        **vid_headers,
                    },
                )
            )

        vid_headers = self._versioned_commit(bucket, key, write)
        # drop the staging entries but keep the needles (now owned by the
        # final object)
        self.s3.filer.delete(d, recursive=True, delete_data=False)
        root = _xml("CompleteMultipartUploadResult")
        _sub(root, "Location", f"{tls.scheme()}://{self.s3.url}/{bucket}/{key}")
        _sub(root, "Bucket", bucket)
        _sub(root, "Key", key)
        _sub(root, "ETag", f'"{etag}"')
        self._reply(200, _render(root), headers=vid_headers)

    def _abort_multipart(self, bucket, key, upload_id):
        if not self._valid_upload(upload_id):
            return
        d = self._upload_dir(bucket, upload_id)
        if self.s3.filer.lookup(d) is not None:
            self.s3.filer.delete(d, recursive=True)
        self._reply(204)
