"""S3 bucket policy engine — resource-based access policies evaluated
before identity grants, mirror of the reference's bucket policy checks
[ref: weed/s3api policy handling — mount empty; SURVEY.md §2.1 "S3
gateway" row].

A policy is the standard AWS JSON document:

    {"Version": "2012-10-17",
     "Statement": [{"Sid": "...", "Effect": "Allow"|"Deny",
                    "Principal": "*" | {"AWS": "*"|name|[names]},
                    "Action": "s3:GetObject" | ["s3:*", ...],
                    "Resource": "arn:aws:s3:::bucket/prefix*" | [...]}]}

Evaluation follows IAM's order: an explicit Deny in any matching
statement wins over everything; otherwise a matching Allow grants
(including to anonymous principals — this is how public-read buckets
work); otherwise the decision falls through to identity grants.

Principal values accept "*" (everyone, including anonymous), a bare
identity name or access key, or an IAM-user ARN whose trailing
``user/<name>`` names the identity. Anonymous callers match ONLY "*".
Action and Resource match with case-preserving ``*``/``?`` wildcards
(actions compare case-insensitively, per AWS).

Version-granular requests are evaluated under the separate AWS action
names — ``s3:GetObjectVersion`` for ?versionId reads,
``s3:DeleteObjectVersion`` for permanent versionId deletes, and
``s3:ListBucketVersions`` for ?versions listings — never under the base
``s3:GetObject``/``s3:DeleteObject``/``s3:ListBucket`` names, so a
public-read grant cannot expose historical versions and a Deny written
against the *Version names actually matches (the server derives the
action name in ``_s3_action_name``).
"""

from __future__ import annotations

import fnmatch
import json
import re
from typing import Optional, Union

ARN_PREFIX = "arn:aws:s3:::"

_EFFECTS = ("Allow", "Deny")


class PolicyError(ValueError):
    """Malformed policy document (maps to S3's MalformedPolicy)."""


def _as_list(v: Union[str, list, None]) -> list:
    if v is None:
        return []
    return v if isinstance(v, list) else [v]


def parse_policy(raw: bytes, bucket: str) -> dict:
    """Validate and normalize a policy document for `bucket`.

    Every Resource must target this bucket — accepting a statement about
    another bucket would silently never match and hide operator typos
    (AWS rejects cross-bucket resources in PutBucketPolicy the same way).
    """
    try:
        doc = json.loads(raw.decode("utf-8"))
    except (UnicodeDecodeError, ValueError) as e:
        raise PolicyError(f"not valid JSON: {e}") from e
    if not isinstance(doc, dict):
        raise PolicyError("policy must be a JSON object")
    stmts = doc.get("Statement")
    if not isinstance(stmts, list) or not stmts:
        raise PolicyError("policy needs a non-empty Statement array")
    supported = {"Sid", "Effect", "Principal", "Action", "Resource"}
    for s in stmts:
        if not isinstance(s, dict):
            raise PolicyError("each Statement must be an object")
        # silently ignoring a Condition / NotAction / NotPrincipal /
        # NotResource would turn a conditional Allow into an unconditional
        # grant — reject what evaluate() does not implement, like AWS
        # rejects malformed restrictions, instead of widening access
        unknown = set(s) - supported
        if unknown:
            raise PolicyError(
                f"unsupported Statement field(s): {', '.join(sorted(unknown))}"
            )
        if s.get("Effect") not in _EFFECTS:
            raise PolicyError("Statement.Effect must be Allow or Deny")
        if "Principal" not in s:
            raise PolicyError("Statement.Principal is required")
        if not _as_list(s.get("Action")):
            raise PolicyError("Statement.Action is required")
        resources = _as_list(s.get("Resource"))
        if not resources:
            raise PolicyError("Statement.Resource is required")
        for r in resources:
            if not isinstance(r, str) or not r.startswith(ARN_PREFIX):
                raise PolicyError(f"Resource must start with {ARN_PREFIX}")
            target = r[len(ARN_PREFIX) :]
            b = target.split("/", 1)[0]
            if b != bucket:
                raise PolicyError(
                    f"Resource {r!r} does not target bucket {bucket!r}"
                )
    return doc


def _wild(pattern: str, value: str, casefold: bool = False) -> bool:
    if casefold:
        pattern, value = pattern.lower(), value.lower()
    # fnmatch.translate handles * and ? but also [seq] — escape brackets so
    # policy patterns stay the documented two-metacharacter language
    pattern = pattern.replace("[", "[[]")
    return re.fullmatch(fnmatch.translate(pattern), value) is not None


def _principal_matches(principal, identity_name: str, access_key: str, anonymous: bool) -> bool:
    values: list[str] = []
    if principal == "*":
        return True
    if isinstance(principal, dict):
        values = _as_list(principal.get("AWS"))
    elif isinstance(principal, (str, list)):
        values = _as_list(principal)
    for v in values:
        if not isinstance(v, str):
            continue
        if v == "*":
            return True
        if anonymous:
            continue  # anonymous matches only the universal principal
        name = v.rsplit("user/", 1)[-1] if v.startswith("arn:") else v
        if name in (identity_name, access_key):
            return True
    return False


def evaluate(
    policy: Optional[dict],
    *,
    identity_name: str,
    access_key: str,
    anonymous: bool,
    action: str,
    resource: str,
) -> Optional[bool]:
    """-> False on an explicit Deny match, True on an Allow match, None
    when no statement matches (caller falls back to identity grants).

    `action` is an s3:* action name; `resource` is the full ARN of the
    bucket or object being touched."""
    if not policy:
        return None
    decision: Optional[bool] = None
    for s in policy.get("Statement", []):
        if not _principal_matches(
            s.get("Principal"), identity_name, access_key, anonymous
        ):
            continue
        if not any(_wild(a, action, casefold=True) for a in _as_list(s.get("Action"))):
            continue
        if not any(_wild(r, resource) for r in _as_list(s.get("Resource"))):
            continue
        if s.get("Effect") == "Deny":
            return False  # explicit deny: nothing can override it
        decision = True
    return decision
