"""Volume & collection shell commands — volume.list / volume.delete /
volume.mark / volume.vacuum / volume.fix.replication / collection.list,
mirroring weed/shell/command_volume_*.go and command_collection_list.go
[VERIFY: mount empty; SURVEY.md §2.1 "Shell (ops)"]."""

from __future__ import annotations

from typing import TextIO

from seaweedfs_tpu.ec.shard_bits import ShardBits
from seaweedfs_tpu.shell import (
    CommandEnv,
    ShellCommand,
    ShellError,
    grpc_addr,
    parse_flags,
    register,
)
from seaweedfs_tpu.storage.super_block import ReplicaPlacement




def do_volume_list(args: list[str], env: CommandEnv, w: TextIO) -> None:
    topo = env.volume_list()
    w.write(f"volume size limit: {topo.get('volume_size_limit')}\n")
    for dc, racks in sorted(topo.get("data_centers", {}).items()):
        w.write(f"DataCenter {dc}\n")
        for rack, nodes in sorted(racks.items()):
            w.write(f"  Rack {rack}\n")
            for n in nodes:
                w.write(
                    f"    Node {n['url']} (grpc :{n['grpc_port']}) "
                    f"slots {len(n.get('volumes', []))}/{n.get('max_volume_count')}\n"
                )
                for v in sorted(n.get("volumes", []), key=lambda v: int(v["id"])):
                    w.write(
                        f"      volume {v['id']} collection={v.get('collection', '')!r} "
                        f"size={v.get('size', 0)} files={v.get('file_count', 0)} "
                        f"del={v.get('delete_count', 0)} "
                        f"ro={v.get('read_only', False)} rp={v.get('replica_placement')}\n"
                    )
                for e in sorted(n.get("ec_shards", []), key=lambda e: int(e["volume_id"])):
                    sids = ShardBits(e.get("shard_bits", 0)).shard_ids()
                    w.write(f"      ec volume {e['volume_id']} shards {sids}\n")


register(
    ShellCommand(
        "volume.list",
        "volume.list\n\tprint the cluster topology: dc/rack/node/volumes/ec shards",
        do_volume_list,
    )
)


def _locations_of(env: CommandEnv, vid: int) -> list[dict]:
    return [
        n
        for n in env.topology_nodes()
        if any(int(v["id"]) == vid for v in n.get("volumes", []))
    ]


def do_volume_delete(args: list[str], env: CommandEnv, w: TextIO) -> None:
    fl = parse_flags(args, volumeId=0)
    env.confirm_locked()
    if not fl.volumeId:
        raise ShellError("volume.delete -volumeId <id>")
    locs = _locations_of(env, fl.volumeId)
    if not locs:
        raise ShellError(f"volume {fl.volumeId} not found")
    for n in locs:
        env.vs_call(grpc_addr(n), "VolumeDelete", {"volume_id": fl.volumeId})
    w.write(f"volume.delete {fl.volumeId}: removed from {[n['url'] for n in locs]}\n")


register(
    ShellCommand(
        "volume.delete",
        "volume.delete -volumeId <id>\n\tdelete a volume from every replica holder",
        do_volume_delete,
    )
)


def do_volume_mark(args: list[str], env: CommandEnv, w: TextIO) -> None:
    fl = parse_flags(args, volumeId=0, readonly=False, writable=False)
    env.confirm_locked()
    if not fl.volumeId or fl.readonly == fl.writable:
        raise ShellError("volume.mark -volumeId <id> (-readonly | -writable)")
    method = "VolumeMarkReadonly" if fl.readonly else "VolumeMarkWritable"
    locs = _locations_of(env, fl.volumeId)
    if not locs:
        raise ShellError(f"volume {fl.volumeId} not found")
    for n in locs:
        env.vs_call(grpc_addr(n), method, {"volume_id": fl.volumeId})
    w.write(f"volume.mark {fl.volumeId}: {'readonly' if fl.readonly else 'writable'}\n")


register(
    ShellCommand(
        "volume.mark",
        "volume.mark -volumeId <id> (-readonly | -writable)\n\tflip a volume's "
        "write protection on all replicas",
        do_volume_mark,
    )
)


def do_volume_vacuum(args: list[str], env: CommandEnv, w: TextIO) -> None:
    """Compact volumes to reclaim deleted-needle space
    (topology_vacuum.go analog, operator-driven)."""
    fl = parse_flags(args, volumeId=0, garbageThreshold=0.3)
    env.confirm_locked()
    nodes = env.topology_nodes()
    done = 0
    for n in nodes:
        for v in n.get("volumes", []):
            vid = int(v["id"])
            if fl.volumeId and vid != fl.volumeId:
                continue
            if v.get("read_only"):  # frozen volumes refuse compaction
                continue
            if not fl.volumeId and float(v.get("garbage_ratio", 0.0)) < fl.garbageThreshold:
                continue
            resp = env.vs_call(grpc_addr(n), "VolumeCompact", {"volume_id": vid})
            w.write(
                f"volume.vacuum {vid} on {n['url']}: "
                f"{resp.get('bytes_before')} -> {resp.get('bytes_after')} bytes\n"
            )
            done += 1
    if not done:
        w.write("volume.vacuum: nothing to do\n")


register(
    ShellCommand(
        "volume.vacuum",
        "volume.vacuum [-volumeId <id>] [-garbageThreshold 0.3]\n\tcompact volumes "
        "whose deleted fraction exceeds the threshold",
        do_volume_vacuum,
    )
)


def _placement_candidates(
    nodes: list[dict], holders: list[dict], rp: ReplicaPlacement
) -> list[dict]:
    """Candidate targets ordered so the xyz placement deficits are restored
    first (same placement predicate as Topology.place_replicas): count the
    surviving holders per category relative to the primary, then prefer
    nodes that fill an unmet category."""
    primary = holders[0]
    held = {h["url"] for h in holders}

    def category(node: dict) -> str:
        if node["data_center"] != primary["data_center"]:
            return "diff_dc"
        if node["rack"] != primary["rack"]:
            return "diff_rack"
        return "same_rack"

    have = {"same_rack": 0, "diff_rack": 0, "diff_dc": 0}
    for h in holders[1:]:
        have[category(h)] += 1
    deficit = {
        "same_rack": rp.same_rack - have["same_rack"],
        "diff_rack": rp.diff_rack - have["diff_rack"],
        "diff_dc": rp.diff_dc - have["diff_dc"],
    }
    out = [m for m in nodes if m["url"] not in held]
    out.sort(
        key=lambda m: (
            -min(deficit[category(m)], 1),  # nodes filling an unmet slot first
            len(m.get("volumes", [])) + len(m.get("ec_shards", [])),
        )
    )
    return out


def do_volume_fix_replication(args: list[str], env: CommandEnv, w: TextIO) -> None:
    """Re-replicate under-replicated volumes (command_volume_fix_replication.go
    analog): VolumeCopy the .dat/.idx onto a fresh node."""
    fl = parse_flags(args, noFix=False)
    if not fl.noFix:
        env.confirm_locked()
    nodes = env.topology_nodes()
    fixed = checked = 0
    seen: set[int] = set()
    for n in nodes:
        for v in n.get("volumes", []):
            vid = int(v["id"])
            if vid in seen:
                continue
            seen.add(vid)
            rp = ReplicaPlacement.parse(v.get("replica_placement", "000"))
            want = rp.copy_count
            holders = [
                m
                for m in nodes
                if any(int(x["id"]) == vid for x in m.get("volumes", []))
            ]
            checked += 1
            if len(holders) >= want:
                continue
            w.write(
                f"volume {vid}: {len(holders)}/{want} replicas "
                f"({[h['url'] for h in holders]})\n"
            )
            if fl.noFix:
                continue
            candidates = _placement_candidates(nodes, holders, rp)
            src = holders[0]
            was_writable = not v.get("read_only", False)
            # freeze the survivors during the copy — writes landing mid-copy
            # would be missing from the new replica (same rule as ec.encode)
            if was_writable:
                for h in holders:
                    env.vs_call(grpc_addr(h), "VolumeMarkReadonly", {"volume_id": vid})
            try:
                for dst in candidates[: want - len(holders)]:
                    env.vs_call(
                        grpc_addr(dst),
                        "VolumeCopy",
                        {
                            "volume_id": vid,
                            "collection": v.get("collection", ""),
                            "source_data_node": grpc_addr(src),
                            # lands frozen; thawed with the others below
                            "read_only": True,
                        },
                    )
                    w.write(f"volume {vid}: copied {src['url']} -> {dst['url']}\n")
                    fixed += 1
                    holders.append(dst)
            finally:
                if was_writable:
                    for h in holders:
                        try:
                            env.vs_call(
                                grpc_addr(h), "VolumeMarkWritable", {"volume_id": vid}
                            )
                        except Exception:  # noqa: BLE001 — best-effort thaw
                            pass
    w.write(f"volume.fix.replication: checked {checked}, fixed {fixed}\n")


register(
    ShellCommand(
        "volume.fix.replication",
        "volume.fix.replication [-noFix]\n\tdetect under-replicated volumes and "
        "copy them to fresh nodes",
        do_volume_fix_replication,
    )
)


def _move_volume(env: CommandEnv, by_url: dict, holders: list[str],
                 vid: int, v: dict, src_url: str, dst_url: str) -> None:
    """Freeze/copy/delete/thaw one volume move — shared by volume.balance
    and volume.move. Freezing consults the LIVE VolumeStatus (the
    heartbeat-stale topology flag could let a write land mid-copy and be
    lost with the source delete); failure paths thaw exactly what was
    frozen, source included."""
    status = env.vs_call(grpc_addr(by_url[src_url]), "VolumeStatus", {"volume_id": vid})
    was_writable = not status.get("read_only", False)
    frozen: list[str] = []
    moved = False
    try:
        if was_writable:
            for u in holders:  # inside try: a failed freeze still thaws
                env.vs_call(grpc_addr(by_url[u]), "VolumeMarkReadonly", {"volume_id": vid})
                frozen.append(u)
        env.vs_call(
            grpc_addr(by_url[dst_url]),
            "VolumeCopy",
            {
                "volume_id": vid,
                "collection": v.get("collection", ""),
                "source_data_node": grpc_addr(by_url[src_url]),
                "read_only": True,
            },
        )
        env.vs_call(grpc_addr(by_url[src_url]), "VolumeDelete", {"volume_id": vid})
        moved = True
    finally:
        if was_writable:
            # success: thaw survivors + destination (source copy is gone).
            # Failure: thaw EXACTLY what was frozen, source included — a
            # failed move must never leave the volume read-only until an
            # operator notices.
            thaw = (
                [u for u in holders if u != src_url] + [dst_url]
                if moved
                else frozen
            )
            for u in thaw:
                try:
                    env.vs_call(
                        grpc_addr(by_url[u]), "VolumeMarkWritable", {"volume_id": vid}
                    )
                except Exception:  # noqa: BLE001 — best-effort thaw
                    pass


def do_volume_balance(args: list[str], env: CommandEnv, w: TextIO) -> None:
    """Even volume counts across nodes (command_volume_balance.go analog):
    move whole volumes (VolumeCopy .dat/.idx, then delete the source copy)
    from the fullest node to the emptiest until counts differ by <=1,
    never co-locating two replicas of one volume. Writable volumes are
    frozen on every holder for the move (a write landing mid-copy would
    be missing from the destination) and thawed after."""
    fl = parse_flags(args, collection="", noApply=False)
    env.confirm_locked()
    nodes = env.topology_nodes()
    if len(nodes) < 2:
        w.write("volume.balance: need >=2 nodes\n")
        return
    by_url = {n["url"]: n for n in nodes}
    placement: dict[str, dict[int, dict]] = {
        n["url"]: {int(v["id"]): v for v in n.get("volumes", [])} for n in nodes
    }
    moves = 0
    while True:
        urls = sorted(placement, key=lambda u: len(placement[u]))
        lightest, heaviest = urls[0], urls[-1]
        if len(placement[heaviest]) - len(placement[lightest]) <= 1:
            break
        candidate = None
        for vid, v in sorted(placement[heaviest].items()):
            if fl.collection and v.get("collection", "") != fl.collection:
                continue
            if vid in placement[lightest]:  # replica already there
                continue
            if v.get("disk_type") == "remote":
                continue  # tiered: no local .dat to stream
            candidate = (vid, v)
            break
        if candidate is None:
            break
        vid, v = candidate
        if fl.noApply:
            w.write(f"volume.balance (dry): would move {vid} {heaviest} -> {lightest}\n")
            placement[lightest][vid] = v
            del placement[heaviest][vid]
            moves += 1
            continue
        holders = [u for u in placement if vid in placement[u]]
        _move_volume(env, by_url, holders, vid, v, heaviest, lightest)
        placement[lightest][vid] = v
        del placement[heaviest][vid]
        w.write(f"volume.balance: moved {vid} {heaviest} -> {lightest}\n")
        moves += 1
    w.write(f"volume.balance: {moves} moves\n")


def do_volume_move(args: list[str], env: CommandEnv, w: TextIO) -> None:
    """Move one volume to a named node (command_volume_move.go analog):
    the targeted form of volume.balance's move, same freeze/copy/delete/
    thaw discipline."""
    fl = parse_flags(args, volumeId=0, target="")
    env.confirm_locked()
    if not fl.volumeId or not fl.target:
        raise ShellError("volume.move needs -volumeId and -target <url>")
    nodes = env.topology_nodes()
    by_url = {n["url"]: n for n in nodes}
    dst = by_url.get(fl.target)
    if dst is None:
        raise ShellError(f"unknown node {fl.target!r} ({sorted(by_url)})")
    src = next(
        (
            n
            for n in nodes
            if any(int(v["id"]) == fl.volumeId for v in n.get("volumes", []))
        ),
        None,
    )
    if src is None:
        raise ShellError(f"volume {fl.volumeId} not found on any node")
    if src["url"] == fl.target:
        w.write(f"volume.move: {fl.volumeId} already on {fl.target}\n")
        return
    if any(int(v["id"]) == fl.volumeId for v in dst.get("volumes", [])):
        raise ShellError(f"node {fl.target} already holds a replica of {fl.volumeId}")
    v = next(v for v in src["volumes"] if int(v["id"]) == fl.volumeId)
    if v.get("disk_type") == "remote":
        raise ShellError(f"volume {fl.volumeId} is tiered — no local .dat to move")
    holders = [
        n["url"]
        for n in nodes
        if any(int(x["id"]) == fl.volumeId for x in n.get("volumes", []))
    ]
    _move_volume(env, by_url, holders, fl.volumeId, v, src["url"], fl.target)
    w.write(f"volume.move: {fl.volumeId} {src['url']} -> {fl.target}\n")


register(
    ShellCommand(
        "volume.move",
        "volume.move -volumeId <id> -target <url>\n\tmove a volume to a "
        "specific node",
        do_volume_move,
    )
)


register(
    ShellCommand(
        "volume.balance",
        "volume.balance [-collection c] [-noApply]\n\teven volume counts across "
        "nodes by moving whole volumes",
        do_volume_balance,
    )
)


def do_collection_list(args: list[str], env: CommandEnv, w: TextIO) -> None:
    topo = env.volume_list()
    names = set(topo.get("ec_collections", {}).values())
    for racks in topo.get("data_centers", {}).values():
        for nodes in racks.values():
            for n in nodes:
                for v in n.get("volumes", []):
                    names.add(v.get("collection", ""))
    for name in sorted(names):
        w.write(f"collection: {name!r}\n")


register(
    ShellCommand(
        "collection.list",
        "collection.list\n\tlist all collections present in the cluster",
        do_collection_list,
    )
)


def _parse_dest(dest: str) -> dict:
    """Parse a tier destination: 'local:/path' or
    's3:endpoint/bucket[:accessKey:secretKey]'."""
    vendor, _, rest = dest.partition(":")
    if vendor == "local":
        return {"vendor": "local", "root": rest}
    if vendor == "s3":
        parts = rest.split(":")
        endpoint_bucket = parts[0]
        endpoint, _, bucket = endpoint_bucket.rpartition("/")
        out = {"vendor": "s3", "endpoint": endpoint, "bucket": bucket}
        if len(parts) >= 3:
            out["access_key"], out["secret_key"] = parts[1], parts[2]
        return out
    raise ShellError(f"bad -dest {dest!r} (local:/path | s3:host:port/bucket[:ak:sk])")


def do_volume_tier_move(args: list[str], env: CommandEnv, w: TextIO) -> None:
    """Move cold volumes' .dat files to remote storage
    (command_volume_tier_move.go analog)."""
    fl = parse_flags(args, volumeId=0, dest="", keyPrefix="volumes/")
    if not fl.volumeId or not fl.dest:
        raise ShellError("volume.tier.move needs -volumeId and -dest")
    env.confirm_locked()
    destination = _parse_dest(fl.dest)
    for n in env.topology_nodes():
        for v in n.get("volumes", []):
            if int(v["id"]) != fl.volumeId:
                continue
            resp = env.vs_call(
                grpc_addr(n),
                "VolumeTierMove",
                {
                    "volume_id": fl.volumeId,
                    "destination": destination,
                    "key_prefix": fl.keyPrefix,
                },
            )
            w.write(
                f"volume.tier.move {fl.volumeId} on {n['url']}: "
                f"{resp.get('size')} bytes -> {resp.get('key')}\n"
            )
            return
    raise ShellError(f"volume {fl.volumeId} not found in the topology")


register(
    ShellCommand(
        "volume.tier.move",
        "volume.tier.move -volumeId <id> -dest local:/path|s3:host:port/bucket[:ak:sk] "
        "[-keyPrefix volumes/]\n\tmove a volume's .dat to remote storage (reads keep working)",
        do_volume_tier_move,
    )
)


def do_volume_tier_fetch(args: list[str], env: CommandEnv, w: TextIO) -> None:
    """Bring a tiered volume's .dat back to local disk."""
    fl = parse_flags(args, volumeId=0)
    if not fl.volumeId:
        raise ShellError("volume.tier.fetch needs -volumeId")
    env.confirm_locked()
    for n in env.topology_nodes():
        for v in n.get("volumes", []):
            if int(v["id"]) != fl.volumeId:
                continue
            resp = env.vs_call(
                grpc_addr(n), "VolumeTierFetch", {"volume_id": fl.volumeId}
            )
            w.write(
                f"volume.tier.fetch {fl.volumeId} on {n['url']}: "
                f"{resp.get('size')} bytes local again\n"
            )
            return
    raise ShellError(f"volume {fl.volumeId} not found in the topology")


register(
    ShellCommand(
        "volume.tier.fetch",
        "volume.tier.fetch -volumeId <id>\n\tdownload a tiered volume's .dat back to local disk",
        do_volume_tier_fetch,
    )
)
