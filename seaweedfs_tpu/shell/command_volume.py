"""Volume & collection shell commands — volume.list / volume.delete /
volume.mark / volume.vacuum / volume.fix.replication / collection.list,
mirroring weed/shell/command_volume_*.go and command_collection_list.go
[VERIFY: mount empty; SURVEY.md §2.1 "Shell (ops)"]."""

from __future__ import annotations

import time
from typing import TextIO

from seaweedfs_tpu.ec.shard_bits import ShardBits
from seaweedfs_tpu.shell import (
    CommandEnv,
    ShellCommand,
    ShellError,
    grpc_addr,
    parse_flags,
    register,
)
from seaweedfs_tpu.storage.super_block import ReplicaPlacement




def do_volume_list(args: list[str], env: CommandEnv, w: TextIO) -> None:
    topo = env.volume_list()
    w.write(f"volume size limit: {topo.get('volume_size_limit')}\n")
    for dc, racks in sorted(topo.get("data_centers", {}).items()):
        w.write(f"DataCenter {dc}\n")
        for rack, nodes in sorted(racks.items()):
            w.write(f"  Rack {rack}\n")
            for n in nodes:
                w.write(
                    f"    Node {n['url']} (grpc :{n['grpc_port']}) "
                    f"slots {len(n.get('volumes', []))}/{n.get('max_volume_count')}\n"
                )
                for v in sorted(n.get("volumes", []), key=lambda v: int(v["id"])):
                    w.write(
                        f"      volume {v['id']} collection={v.get('collection', '')!r} "
                        f"size={v.get('size', 0)} files={v.get('file_count', 0)} "
                        f"del={v.get('delete_count', 0)} "
                        f"ro={v.get('read_only', False)} rp={v.get('replica_placement')}\n"
                    )
                for e in sorted(n.get("ec_shards", []), key=lambda e: int(e["volume_id"])):
                    sids = ShardBits(e.get("shard_bits", 0)).shard_ids()
                    w.write(f"      ec volume {e['volume_id']} shards {sids}\n")


register(
    ShellCommand(
        "volume.list",
        "volume.list\n\tprint the cluster topology: dc/rack/node/volumes/ec shards",
        do_volume_list,
    )
)


def _locations_of(env: CommandEnv, vid: int) -> list[dict]:
    return [
        n
        for n in env.topology_nodes()
        if any(int(v["id"]) == vid for v in n.get("volumes", []))
    ]


def do_volume_delete(args: list[str], env: CommandEnv, w: TextIO) -> None:
    fl = parse_flags(args, volumeId=0)
    env.confirm_locked()
    if not fl.volumeId:
        raise ShellError("volume.delete -volumeId <id>")
    locs = _locations_of(env, fl.volumeId)
    if not locs:
        raise ShellError(f"volume {fl.volumeId} not found")
    for n in locs:
        env.vs_call(grpc_addr(n), "VolumeDelete", {"volume_id": fl.volumeId})
    w.write(f"volume.delete {fl.volumeId}: removed from {[n['url'] for n in locs]}\n")


register(
    ShellCommand(
        "volume.delete",
        "volume.delete -volumeId <id>\n\tdelete a volume from every replica holder",
        do_volume_delete,
    )
)


def do_volume_mark(args: list[str], env: CommandEnv, w: TextIO) -> None:
    fl = parse_flags(args, volumeId=0, readonly=False, writable=False)
    env.confirm_locked()
    if not fl.volumeId or fl.readonly == fl.writable:
        raise ShellError("volume.mark -volumeId <id> (-readonly | -writable)")
    method = "VolumeMarkReadonly" if fl.readonly else "VolumeMarkWritable"
    locs = _locations_of(env, fl.volumeId)
    if not locs:
        raise ShellError(f"volume {fl.volumeId} not found")
    for n in locs:
        env.vs_call(grpc_addr(n), method, {"volume_id": fl.volumeId})
    w.write(f"volume.mark {fl.volumeId}: {'readonly' if fl.readonly else 'writable'}\n")


register(
    ShellCommand(
        "volume.mark",
        "volume.mark -volumeId <id> (-readonly | -writable)\n\tflip a volume's "
        "write protection on all replicas",
        do_volume_mark,
    )
)


def do_volume_vacuum(args: list[str], env: CommandEnv, w: TextIO) -> None:
    """Compact volumes to reclaim deleted-needle space
    (topology_vacuum.go analog, operator-driven)."""
    fl = parse_flags(args, volumeId=0, garbageThreshold=0.3)
    env.confirm_locked()
    nodes = env.topology_nodes()
    done = 0
    for n in nodes:
        for v in n.get("volumes", []):
            vid = int(v["id"])
            if fl.volumeId and vid != fl.volumeId:
                continue
            if v.get("read_only"):  # frozen volumes refuse compaction
                continue
            if not fl.volumeId and float(v.get("garbage_ratio", 0.0)) < fl.garbageThreshold:
                continue
            resp = env.vs_call(grpc_addr(n), "VolumeCompact", {"volume_id": vid})
            w.write(
                f"volume.vacuum {vid} on {n['url']}: "
                f"{resp.get('bytes_before')} -> {resp.get('bytes_after')} bytes\n"
            )
            done += 1
    if not done:
        w.write("volume.vacuum: nothing to do\n")


register(
    ShellCommand(
        "volume.vacuum",
        "volume.vacuum [-volumeId <id>] [-garbageThreshold 0.3]\n\tcompact volumes "
        "whose deleted fraction exceeds the threshold",
        do_volume_vacuum,
    )
)


def _placement_candidates(
    nodes: list[dict], holders: list[dict], rp: ReplicaPlacement
) -> list[dict]:
    """Candidate targets ordered so the xyz placement deficits are restored
    first (same placement predicate as Topology.place_replicas): count the
    surviving holders per category relative to the primary, then prefer
    nodes that fill an unmet category."""
    primary = holders[0]
    held = {h["url"] for h in holders}

    def category(node: dict) -> str:
        if node["data_center"] != primary["data_center"]:
            return "diff_dc"
        if node["rack"] != primary["rack"]:
            return "diff_rack"
        return "same_rack"

    have = {"same_rack": 0, "diff_rack": 0, "diff_dc": 0}
    for h in holders[1:]:
        have[category(h)] += 1
    deficit = {
        "same_rack": rp.same_rack - have["same_rack"],
        "diff_rack": rp.diff_rack - have["diff_rack"],
        "diff_dc": rp.diff_dc - have["diff_dc"],
    }
    out = [m for m in nodes if m["url"] not in held]
    out.sort(
        key=lambda m: (
            -min(deficit[category(m)], 1),  # nodes filling an unmet slot first
            len(m.get("volumes", [])) + len(m.get("ec_shards", [])),
        )
    )
    return out


def do_volume_fix_replication(args: list[str], env: CommandEnv, w: TextIO) -> None:
    """Re-replicate under-replicated volumes (command_volume_fix_replication.go
    analog): VolumeCopy the .dat/.idx onto a fresh node."""
    fl = parse_flags(args, noFix=False)
    if not fl.noFix:
        env.confirm_locked()
    nodes = env.topology_nodes()
    fixed = checked = 0
    seen: set[int] = set()
    for n in nodes:
        for v in n.get("volumes", []):
            vid = int(v["id"])
            if vid in seen:
                continue
            seen.add(vid)
            rp = ReplicaPlacement.parse(v.get("replica_placement", "000"))
            want = rp.copy_count
            holders = [
                m
                for m in nodes
                if any(int(x["id"]) == vid for x in m.get("volumes", []))
            ]
            checked += 1
            if len(holders) >= want:
                continue
            w.write(
                f"volume {vid}: {len(holders)}/{want} replicas "
                f"({[h['url'] for h in holders]})\n"
            )
            if fl.noFix:
                continue
            candidates = _placement_candidates(nodes, holders, rp)
            src = holders[0]
            was_writable = not v.get("read_only", False)
            # freeze the survivors during the copy — writes landing mid-copy
            # would be missing from the new replica (same rule as ec.encode)
            if was_writable:
                for h in holders:
                    env.vs_call(grpc_addr(h), "VolumeMarkReadonly", {"volume_id": vid})
            try:
                for dst in candidates[: want - len(holders)]:
                    env.vs_call(
                        grpc_addr(dst),
                        "VolumeCopy",
                        {
                            "volume_id": vid,
                            "collection": v.get("collection", ""),
                            "source_data_node": grpc_addr(src),
                            # lands frozen; thawed with the others below
                            "read_only": True,
                        },
                    )
                    w.write(f"volume {vid}: copied {src['url']} -> {dst['url']}\n")
                    fixed += 1
                    holders.append(dst)
            finally:
                if was_writable:
                    for h in holders:
                        try:
                            env.vs_call(
                                grpc_addr(h), "VolumeMarkWritable", {"volume_id": vid}
                            )
                        except Exception:  # noqa: BLE001 — best-effort thaw
                            pass
    w.write(f"volume.fix.replication: checked {checked}, fixed {fixed}\n")


register(
    ShellCommand(
        "volume.fix.replication",
        "volume.fix.replication [-noFix]\n\tdetect under-replicated volumes and "
        "copy them to fresh nodes",
        do_volume_fix_replication,
    )
)


def _move_volume(env: CommandEnv, by_url: dict, holders: list[str],
                 vid: int, v: dict, src_url: str, dst_url: str) -> None:
    """Freeze/copy/delete/thaw one volume move — shared by volume.balance
    and volume.move. Freezing consults the LIVE VolumeStatus (the
    heartbeat-stale topology flag could let a write land mid-copy and be
    lost with the source delete); failure paths thaw exactly what was
    frozen, source included."""
    status = env.vs_call(grpc_addr(by_url[src_url]), "VolumeStatus", {"volume_id": vid})
    was_writable = not status.get("read_only", False)
    frozen: list[str] = []
    moved = False
    try:
        if was_writable:
            for u in holders:  # inside try: a failed freeze still thaws
                env.vs_call(grpc_addr(by_url[u]), "VolumeMarkReadonly", {"volume_id": vid})
                frozen.append(u)
        env.vs_call(
            grpc_addr(by_url[dst_url]),
            "VolumeCopy",
            {
                "volume_id": vid,
                "collection": v.get("collection", ""),
                "source_data_node": grpc_addr(by_url[src_url]),
                "read_only": True,
            },
        )
        env.vs_call(grpc_addr(by_url[src_url]), "VolumeDelete", {"volume_id": vid})
        moved = True
    finally:
        if was_writable:
            # success: thaw survivors + destination (source copy is gone).
            # Failure: thaw EXACTLY what was frozen, source included — a
            # failed move must never leave the volume read-only until an
            # operator notices.
            thaw = (
                [u for u in holders if u != src_url] + [dst_url]
                if moved
                else frozen
            )
            for u in thaw:
                try:
                    env.vs_call(
                        grpc_addr(by_url[u]), "VolumeMarkWritable", {"volume_id": vid}
                    )
                except Exception:  # noqa: BLE001 — best-effort thaw
                    pass


def do_volume_balance(args: list[str], env: CommandEnv, w: TextIO) -> None:
    """Even volume counts across nodes (command_volume_balance.go analog):
    move whole volumes (VolumeCopy .dat/.idx, then delete the source copy)
    from the fullest node to the emptiest until counts differ by <=1,
    never co-locating two replicas of one volume. Writable volumes are
    frozen on every holder for the move (a write landing mid-copy would
    be missing from the destination) and thawed after."""
    fl = parse_flags(args, collection="", noApply=False)
    env.confirm_locked()
    nodes = env.topology_nodes()
    if len(nodes) < 2:
        w.write("volume.balance: need >=2 nodes\n")
        return
    by_url = {n["url"]: n for n in nodes}
    placement: dict[str, dict[int, dict]] = {
        n["url"]: {int(v["id"]): v for v in n.get("volumes", [])} for n in nodes
    }
    moves = 0
    while True:
        urls = sorted(placement, key=lambda u: len(placement[u]))
        lightest, heaviest = urls[0], urls[-1]
        if len(placement[heaviest]) - len(placement[lightest]) <= 1:
            break
        candidate = None
        for vid, v in sorted(placement[heaviest].items()):
            if fl.collection and v.get("collection", "") != fl.collection:
                continue
            if vid in placement[lightest]:  # replica already there
                continue
            if v.get("disk_type") == "remote":
                continue  # tiered: no local .dat to stream
            candidate = (vid, v)
            break
        if candidate is None:
            break
        vid, v = candidate
        if fl.noApply:
            w.write(f"volume.balance (dry): would move {vid} {heaviest} -> {lightest}\n")
            placement[lightest][vid] = v
            del placement[heaviest][vid]
            moves += 1
            continue
        holders = [u for u in placement if vid in placement[u]]
        _move_volume(env, by_url, holders, vid, v, heaviest, lightest)
        placement[lightest][vid] = v
        del placement[heaviest][vid]
        w.write(f"volume.balance: moved {vid} {heaviest} -> {lightest}\n")
        moves += 1
    w.write(f"volume.balance: {moves} moves\n")


def do_volume_move(args: list[str], env: CommandEnv, w: TextIO) -> None:
    """Move one volume to a named node (command_volume_move.go analog):
    the targeted form of volume.balance's move, same freeze/copy/delete/
    thaw discipline."""
    fl = parse_flags(args, volumeId=0, target="")
    env.confirm_locked()
    if not fl.volumeId or not fl.target:
        raise ShellError("volume.move needs -volumeId and -target <url>")
    nodes = env.topology_nodes()
    by_url = {n["url"]: n for n in nodes}
    dst = by_url.get(fl.target)
    if dst is None:
        raise ShellError(f"unknown node {fl.target!r} ({sorted(by_url)})")
    src = next(
        (
            n
            for n in nodes
            if any(int(v["id"]) == fl.volumeId for v in n.get("volumes", []))
        ),
        None,
    )
    if src is None:
        raise ShellError(f"volume {fl.volumeId} not found on any node")
    if src["url"] == fl.target:
        w.write(f"volume.move: {fl.volumeId} already on {fl.target}\n")
        return
    if any(int(v["id"]) == fl.volumeId for v in dst.get("volumes", [])):
        raise ShellError(f"node {fl.target} already holds a replica of {fl.volumeId}")
    v = next(v for v in src["volumes"] if int(v["id"]) == fl.volumeId)
    if v.get("disk_type") == "remote":
        raise ShellError(f"volume {fl.volumeId} is tiered — no local .dat to move")
    holders = [
        n["url"]
        for n in nodes
        if any(int(x["id"]) == fl.volumeId for x in n.get("volumes", []))
    ]
    _move_volume(env, by_url, holders, fl.volumeId, v, src["url"], fl.target)
    w.write(f"volume.move: {fl.volumeId} {src['url']} -> {fl.target}\n")


register(
    ShellCommand(
        "volume.move",
        "volume.move -volumeId <id> -target <url>\n\tmove a volume to a "
        "specific node",
        do_volume_move,
    )
)


register(
    ShellCommand(
        "volume.balance",
        "volume.balance [-collection c] [-noApply]\n\teven volume counts across "
        "nodes by moving whole volumes",
        do_volume_balance,
    )
)


def do_collection_list(args: list[str], env: CommandEnv, w: TextIO) -> None:
    topo = env.volume_list()
    names = set(topo.get("ec_collections", {}).values())
    for racks in topo.get("data_centers", {}).values():
        for nodes in racks.values():
            for n in nodes:
                for v in n.get("volumes", []):
                    names.add(v.get("collection", ""))
    for name in sorted(names):
        w.write(f"collection: {name!r}\n")


register(
    ShellCommand(
        "collection.list",
        "collection.list\n\tlist all collections present in the cluster",
        do_collection_list,
    )
)


def _parse_dest(dest: str) -> dict:
    """Parse a tier destination: 'local:/path' or
    's3:endpoint/bucket[:accessKey:secretKey]'."""
    vendor, _, rest = dest.partition(":")
    if vendor == "local":
        return {"vendor": "local", "root": rest}
    if vendor == "s3":
        parts = rest.split(":")
        endpoint_bucket = parts[0]
        endpoint, _, bucket = endpoint_bucket.rpartition("/")
        out = {"vendor": "s3", "endpoint": endpoint, "bucket": bucket}
        if len(parts) >= 3:
            out["access_key"], out["secret_key"] = parts[1], parts[2]
        return out
    raise ShellError(f"bad -dest {dest!r} (local:/path | s3:host:port/bucket[:ak:sk])")


def do_volume_tier_move(args: list[str], env: CommandEnv, w: TextIO) -> None:
    """Move cold volumes' .dat files to remote storage
    (command_volume_tier_move.go analog)."""
    fl = parse_flags(args, volumeId=0, dest="", keyPrefix="volumes/")
    if not fl.volumeId or not fl.dest:
        raise ShellError("volume.tier.move needs -volumeId and -dest")
    env.confirm_locked()
    destination = _parse_dest(fl.dest)
    for n in env.topology_nodes():
        for v in n.get("volumes", []):
            if int(v["id"]) != fl.volumeId:
                continue
            resp = env.vs_call(
                grpc_addr(n),
                "VolumeTierMove",
                {
                    "volume_id": fl.volumeId,
                    "destination": destination,
                    "key_prefix": fl.keyPrefix,
                },
            )
            w.write(
                f"volume.tier.move {fl.volumeId} on {n['url']}: "
                f"{resp.get('size')} bytes -> {resp.get('key')}\n"
            )
            return
    raise ShellError(f"volume {fl.volumeId} not found in the topology")


register(
    ShellCommand(
        "volume.tier.move",
        "volume.tier.move -volumeId <id> -dest local:/path|s3:host:port/bucket[:ak:sk] "
        "[-keyPrefix volumes/]\n\tmove a volume's .dat to remote storage (reads keep working)",
        do_volume_tier_move,
    )
)


def do_volume_tier_fetch(args: list[str], env: CommandEnv, w: TextIO) -> None:
    """Bring a tiered volume's .dat back to local disk."""
    fl = parse_flags(args, volumeId=0)
    if not fl.volumeId:
        raise ShellError("volume.tier.fetch needs -volumeId")
    env.confirm_locked()
    for n in env.topology_nodes():
        for v in n.get("volumes", []):
            if int(v["id"]) != fl.volumeId:
                continue
            resp = env.vs_call(
                grpc_addr(n), "VolumeTierFetch", {"volume_id": fl.volumeId}
            )
            w.write(
                f"volume.tier.fetch {fl.volumeId} on {n['url']}: "
                f"{resp.get('size')} bytes local again\n"
            )
            return
    raise ShellError(f"volume {fl.volumeId} not found in the topology")


register(
    ShellCommand(
        "volume.tier.fetch",
        "volume.tier.fetch -volumeId <id>\n\tdownload a tiered volume's .dat back to local disk",
        do_volume_tier_fetch,
    )
)


def _mount_dispatch(cmd_name: str, method: str):
    """volume.mount / volume.unmount (command_volume_mount.go analog):
    fence a volume off a node (files kept) or bring it back."""

    def do(args: list[str], env: CommandEnv, w: TextIO) -> None:
        fl = parse_flags(args, volumeId=0, node="")
        env.confirm_locked()
        if not fl.volumeId or not fl.node:
            raise ShellError(f"{cmd_name} needs -volumeId and -node <url>")
        by_url = {n["url"]: n for n in env.topology_nodes()}
        n = by_url.get(fl.node)
        if n is None:
            raise ShellError(f"unknown node {fl.node!r} ({sorted(by_url)})")
        env.vs_call(grpc_addr(n), method, {"volume_id": fl.volumeId})
        w.write(f"{cmd_name}: volume {fl.volumeId} on {fl.node}\n")

    return do


register(
    ShellCommand(
        "volume.mount",
        "volume.mount -volumeId <id> -node <url>\n\tre-mount an unmounted volume "
        "from its on-disk files",
        _mount_dispatch("volume.mount", "VolumeMount"),
    )
)
register(
    ShellCommand(
        "volume.unmount",
        "volume.unmount -volumeId <id> -node <url>\n\tstop serving a volume but "
        "keep its files on disk",
        _mount_dispatch("volume.unmount", "VolumeUnmount"),
    )
)


def do_volume_grow(args: list[str], env: CommandEnv, w: TextIO) -> None:
    """Pre-allocate volumes for a layout without waiting for writes to
    trip automatic growth (command_volume_grow.go analog)."""
    fl = parse_flags(args, collection="", replication="", ttl="", count=1)
    env.confirm_locked()
    resp = env.master_call(
        "VolumeGrow",
        {
            "collection": fl.collection,
            "replication": fl.replication,
            "ttl": fl.ttl,
            "count": fl.count,
        },
    )
    w.write(f"volume.grow: {resp.get('grown', 0)} volumes created\n")


register(
    ShellCommand(
        "volume.grow",
        "volume.grow [-collection c] [-replication xyz] [-ttl 7d] [-count N]\n"
        "\tpre-allocate writable volumes for a layout",
        do_volume_grow,
    )
)


def do_collection_delete(args: list[str], env: CommandEnv, w: TextIO) -> None:
    """Delete every volume and EC volume of a collection
    (command_collection_delete.go analog). Requires -force to actually
    destroy data."""
    fl = parse_flags(args, collection="", force=False)
    env.confirm_locked()
    if not fl.collection:
        raise ShellError("collection.delete -collection <name> -force")
    from seaweedfs_tpu.shell.command_ec import _ec_collections

    nodes = env.topology_nodes()
    colls = _ec_collections(env)
    victims_normal: list[tuple[dict, int]] = []
    victims_ec: list[tuple[dict, int]] = []
    for n in nodes:
        for v in n.get("volumes", []):
            if v.get("collection", "") == fl.collection:
                victims_normal.append((n, int(v["id"])))
        for e in n.get("ec_shards", []):
            if colls.get(int(e["volume_id"]), "") == fl.collection:
                victims_ec.append((n, int(e["volume_id"])))
    if not victims_normal and not victims_ec:
        w.write(f"collection.delete: no volumes in {fl.collection!r}\n")
        return
    if not fl.force:
        w.write(
            f"collection.delete (dry): would delete {len(victims_normal)} volume "
            f"replicas and {len(victims_ec)} EC shard sets in {fl.collection!r}; "
            "re-run with -force\n"
        )
        return
    for n, vid in victims_normal:
        env.vs_call(grpc_addr(n), "VolumeDelete", {"volume_id": vid})
    for n, vid in victims_ec:
        env.vs_call(
            grpc_addr(n),
            "VolumeEcShardsDelete",
            {"volume_id": vid, "collection": fl.collection, "shard_ids": []},
        )
    w.write(
        f"collection.delete {fl.collection!r}: removed {len(victims_normal)} volume "
        f"replicas, {len(victims_ec)} EC shard sets\n"
    )


register(
    ShellCommand(
        "collection.delete",
        "collection.delete -collection <name> -force\n\tdelete every volume of a collection",
        do_collection_delete,
    )
)


def do_volume_configure_replication(args: list[str], env: CommandEnv, w: TextIO) -> None:
    """Rewrite a volume's replica placement on every holder
    (command_volume_configure_replication.go analog)."""
    fl = parse_flags(args, volumeId=0, collection="", replication="")
    env.confirm_locked()
    if not fl.replication or (not fl.volumeId and not fl.collection):
        raise ShellError(
            "volume.configure.replication (-volumeId <id> | -collection <c>) "
            "-replication xyz"
        )
    ReplicaPlacement.parse(fl.replication)  # validate before touching disks
    changed = 0
    for n in env.topology_nodes():
        for v in n.get("volumes", []):
            vid = int(v["id"])
            if fl.volumeId and vid != fl.volumeId:
                continue
            if fl.collection and v.get("collection", "") != fl.collection:
                continue
            if v.get("disk_type") == "remote":
                w.write(f"volume {vid} on {n['url']}: tiered, skipped "
                        f"(volume.tier.fetch first)\n")
                continue
            env.vs_call(
                grpc_addr(n),
                "VolumeConfigure",
                {"volume_id": vid, "replication": fl.replication},
            )
            w.write(f"volume {vid} on {n['url']}: replication -> {fl.replication}\n")
            changed += 1
    if not changed:
        raise ShellError("volume.configure.replication: no matching volumes")


register(
    ShellCommand(
        "volume.configure.replication",
        "volume.configure.replication (-volumeId <id> | -collection <c>) -replication xyz\n"
        "\tchange replica placement in the volume superblock on every holder",
        do_volume_configure_replication,
    )
)


def do_volume_delete_empty(args: list[str], env: CommandEnv, w: TextIO) -> None:
    """Delete volumes holding no live needles (command_volume_delete_empty.go
    analog). -force applies; default is a dry run."""
    fl = parse_flags(args, force=False)
    env.confirm_locked()
    nodes = env.topology_nodes()
    seen: set[int] = set()
    deleted = 0
    for n in nodes:
        for v in n.get("volumes", []):
            vid = int(v["id"])
            if vid in seen:
                continue
            seen.add(vid)
            live = int(v.get("file_count", 0)) - int(v.get("delete_count", 0))
            if live > 0:
                continue
            holders = [
                m
                for m in nodes
                if any(int(x["id"]) == vid for x in m.get("volumes", []))
            ]
            if not fl.force:
                w.write(f"volume.deleteEmpty (dry): volume {vid} is empty "
                        f"on {[h['url'] for h in holders]}\n")
                continue
            # the topology counts are heartbeat-stale: freeze every holder
            # (recording the LIVE read_only state, as _move_volume does),
            # then re-check LIVE emptiness — a write acked since the last
            # beat must abort the delete, not be destroyed with the volume
            frozen: list[dict] = []  # holders WE froze (live status said writable)
            still_empty = True
            try:
                for h in holders:
                    st = env.vs_call(grpc_addr(h), "VolumeStatus", {"volume_id": vid})
                    if int(st.get("file_count", 0)) > 0:
                        still_empty = False
                        break
                    if not st.get("read_only", False):
                        env.vs_call(grpc_addr(h), "VolumeMarkReadonly", {"volume_id": vid})
                        frozen.append(h)
                if still_empty:
                    # re-check after the freeze closed the write window
                    for h in holders:
                        st = env.vs_call(grpc_addr(h), "VolumeStatus", {"volume_id": vid})
                        if int(st.get("file_count", 0)) > 0:
                            still_empty = False
                            break
            except Exception:  # noqa: BLE001 — unreachable holder: keep the volume
                still_empty = False
            if not still_empty:
                for h in frozen:  # thaw exactly what we froze, nothing else
                    try:
                        env.vs_call(grpc_addr(h), "VolumeMarkWritable", {"volume_id": vid})
                    except Exception:  # noqa: BLE001 — best-effort thaw
                        pass
                w.write(f"volume.deleteEmpty: {vid} no longer empty, skipped\n")
                continue
            removed: list[dict] = []
            try:
                for h in holders:
                    env.vs_call(grpc_addr(h), "VolumeDelete", {"volume_id": vid})
                    removed.append(h)
            except Exception as e:  # noqa: BLE001 — partial delete: thaw survivors
                survivors = [h for h in frozen if h not in removed]
                for h in survivors:
                    try:
                        env.vs_call(grpc_addr(h), "VolumeMarkWritable", {"volume_id": vid})
                    except Exception:  # noqa: BLE001 — best-effort thaw
                        pass
                w.write(
                    f"volume.deleteEmpty: {vid} partially removed "
                    f"({len(removed)}/{len(holders)}), survivors thawed: {e}\n"
                )
                continue
            w.write(f"volume.deleteEmpty: removed {vid} from {len(holders)} nodes\n")
            deleted += 1
    w.write(f"volume.deleteEmpty: {deleted} volumes removed\n")


register(
    ShellCommand(
        "volume.deleteEmpty",
        "volume.deleteEmpty [-force]\n\tdelete volumes with zero live files from all replicas",
        do_volume_delete_empty,
    )
)


def _needle_ids_of(env: CommandEnv, node: dict, vid: int) -> tuple[dict[int, int], dict[int, int]]:
    """(live id -> size, tombstone-history id -> final_dead) of one replica,
    both fully paged — a dropped tombstone page would misread 'processed
    the delete' as 'missed the write' and resurrect deleted data."""
    out: dict[int, int] = {}
    start = 0
    while True:
        resp = env.vs_call(
            grpc_addr(node),
            "VolumeNeedleIds",
            {"volume_id": vid, "start_from": start, "limit": 65536},
        )
        for row in resp.get("entries", []):
            out[int(row["id"])] = int(row["size"])
        if not resp.get("truncated"):
            break
        start = max(out) + 1
    tombs: dict[int, int] = {}
    start = 0
    while True:
        resp = env.vs_call(
            grpc_addr(node),
            "VolumeNeedleIds",
            {"volume_id": vid, "tombstones": True, "deleted_start_from": start,
             "limit": 65536},
        )
        page = [
            (int(r["id"]), int(r["final_dead"])) for r in resp.get("deleted", [])
        ]
        tombs.update(page)
        if not resp.get("deleted_truncated") or not page:
            return out, tombs
        start = max(k for k, _ in page) + 1


def do_volume_check_disk(args: list[str], env: CommandEnv, w: TextIO) -> None:
    """Compare the replicas of each volume needle-by-needle and copy
    missing needles from the replica that has them
    (command_volume_check_disk.go analog). -fix applies repairs."""
    fl = parse_flags(args, volumeId=0, fix=False)
    env.confirm_locked()
    nodes = env.topology_nodes()
    seen: set[int] = set()
    synced = mismatched = 0
    for n in nodes:
        for v in n.get("volumes", []):
            vid = int(v["id"])
            if vid in seen or (fl.volumeId and vid != fl.volumeId):
                continue
            seen.add(vid)
            holders = [
                m
                for m in nodes
                if any(int(x["id"]) == vid for x in m.get("volumes", []))
            ]
            if len(holders) < 2:
                continue
            state = {h["url"]: _needle_ids_of(env, h, vid) for h in holders}
            live = {u: s[0] for u, s in state.items()}
            tombs = {u: s[1] for u, s in state.items()}
            union: set[int] = set()
            for m in live.values():
                union |= set(m)
            # A FINAL tombstone anywhere means the needle was deleted — the
            # replica still serving it missed the delete, so propagate the
            # delete rather than resurrecting from the lagging replica.
            # EXCEPT when some live holder's own history shows a tombstone
            # followed by a re-write (final state live): that write postdates
            # the delete, so the write wins and is copied out instead.
            final_dead = {
                nid
                for t in tombs.values()
                for nid, dead in t.items()
                if dead
            }
            rewritten = {
                nid
                for u, t in tombs.items()
                for nid, dead in t.items()
                if not dead and nid in live[u]
            }
            delete_these = (union & final_dead) - rewritten
            by_url = {h["url"]: h for h in holders}
            for nid in sorted(delete_these):
                for url, have in sorted(live.items()):
                    if nid not in have:
                        continue
                    mismatched += 1
                    w.write(
                        f"volume {vid} on {url}: needle {nid:x} outlived its "
                        f"delete\n"
                    )
                    if fl.fix:
                        env.vs_call(
                            grpc_addr(by_url[url]),
                            "DeleteNeedle",
                            {"fid": f"{vid},{nid:x}00000000"},
                        )
                        synced += 1
            for url, have in sorted(live.items()):
                missing = union - set(have) - delete_these
                if not missing:
                    continue
                mismatched += 1
                w.write(
                    f"volume {vid} on {url}: missing {len(missing)} needles\n"
                )
                if not fl.fix:
                    continue
                for nid in sorted(missing):
                    # prefer a donor whose history proves its copy postdates
                    # the delete (rewrite evidence); else any live holder
                    donor_url = next(
                        (
                            u
                            for u, t in tombs.items()
                            if nid in live[u] and t.get(nid) == 0
                        ),
                        next(u for u, m in live.items() if nid in m),
                    )
                    blob = env.vs_call(
                        grpc_addr(by_url[donor_url]),
                        "ReadNeedle",
                        {"volume_id": vid, "needle_id": nid},
                    )
                    fid = f"{vid},{nid:x}{int(blob['cookie']):08x}"
                    req = {"fid": fid, "data": blob["data"]}
                    # pass name/mime as b64 so non-UTF-8 bytes survive intact
                    if blob.get("name_b64"):
                        req["name_b64"] = blob["name_b64"]
                    if blob.get("mime_b64"):
                        req["mime_b64"] = blob["mime_b64"]
                    env.vs_call(grpc_addr(by_url[url]), "WriteNeedle", req)
                    synced += 1
    w.write(
        f"volume.check.disk: {mismatched} divergent replicas, "
        f"{synced} needles synced\n"
    )


register(
    ShellCommand(
        "volume.check.disk",
        "volume.check.disk [-volumeId <id>] [-fix]\n\tdiff replica needle sets and "
        "copy missing needles from healthy replicas",
        do_volume_check_disk,
    )
)


def do_volume_server_leave(args: list[str], env: CommandEnv, w: TextIO) -> None:
    """Ask one volume server to stop heartbeating and leave the topology
    (command_volume_server_leave.go analog)."""
    fl = parse_flags(args, node="")
    env.confirm_locked()
    if not fl.node:
        raise ShellError("volumeServer.leave -node <url>")
    by_url = {n["url"]: n for n in env.topology_nodes()}
    n = by_url.get(fl.node)
    if n is None:
        raise ShellError(f"unknown node {fl.node!r} ({sorted(by_url)})")
    env.vs_call(grpc_addr(n), "VolumeServerLeave", {})
    w.write(f"volumeServer.leave: {fl.node} left the cluster\n")


register(
    ShellCommand(
        "volumeServer.leave",
        "volumeServer.leave -node <url>\n\task a volume server to stop heartbeating "
        "and depart the topology (it keeps serving until stopped)",
        do_volume_server_leave,
    )
)


def do_volume_server_evacuate(args: list[str], env: CommandEnv, w: TextIO) -> None:
    """Move every volume and EC shard off one node so it can be retired
    (command_volume_server_evacuate.go analog)."""
    fl = parse_flags(args, node="", noApply=False)
    env.confirm_locked()
    if not fl.node:
        raise ShellError("volumeServer.evacuate -node <url> [-noApply]")
    nodes = env.topology_nodes()
    by_url = {n["url"]: n for n in nodes}
    src = by_url.get(fl.node)
    if src is None:
        raise ShellError(f"unknown node {fl.node!r} ({sorted(by_url)})")
    others = [n for n in nodes if n["url"] != fl.node]
    if not others:
        raise ShellError("volumeServer.evacuate: no other nodes to receive data")

    moved = 0
    # normal volumes: least-loaded target without a replica of the volume
    for v in sorted(src.get("volumes", []), key=lambda v: int(v["id"])):
        vid = int(v["id"])
        if v.get("disk_type") == "remote":
            w.write(f"evacuate: skipping tiered volume {vid} (no local .dat)\n")
            continue
        holders = [
            n["url"]
            for n in nodes
            if any(int(x["id"]) == vid for x in n.get("volumes", []))
        ]
        targets = sorted(
            (n for n in others if n["url"] not in holders),
            key=lambda n: len(n.get("volumes", [])) + len(n.get("ec_shards", [])),
        )
        if not targets:
            raise ShellError(f"evacuate: no replica-free target for volume {vid}")
        dst = targets[0]
        if fl.noApply:
            w.write(f"evacuate (dry): volume {vid} {fl.node} -> {dst['url']}\n")
        else:
            _move_volume(env, by_url, holders, vid, v, fl.node, dst["url"])
            w.write(f"evacuate: volume {vid} {fl.node} -> {dst['url']}\n")
            dst.setdefault("volumes", []).append(v)
        moved += 1

    # EC shards: spread to nodes not already holding shards of that volume
    from seaweedfs_tpu.shell.command_ec import _ec_collections

    colls = _ec_collections(env)
    for e in sorted(src.get("ec_shards", []), key=lambda e: int(e["volume_id"])):
        vid = int(e["volume_id"])
        sids = ShardBits(e.get("shard_bits", 0)).shard_ids()
        collection = colls.get(vid, "")
        for sid in sids:
            targets = sorted(
                others,
                key=lambda n: sum(
                    len(ShardBits(x.get("shard_bits", 0)).shard_ids())
                    for x in n.get("ec_shards", [])
                ),
            )
            # prefer a target without any shard of this volume (spread), else
            # least-loaded (correct but reduces failure independence)
            spread = [
                n
                for n in targets
                if not any(
                    int(x["volume_id"]) == vid for x in n.get("ec_shards", [])
                )
            ]
            dst = (spread or targets)[0]
            if fl.noApply:
                w.write(f"evacuate (dry): ec {vid}.{sid} {fl.node} -> {dst['url']}\n")
                moved += 1
                continue
            has_vid = any(
                int(x["volume_id"]) == vid for x in dst.get("ec_shards", [])
            )
            env.vs_call(
                grpc_addr(dst),
                "VolumeEcShardsCopy",
                {
                    "volume_id": vid,
                    "collection": collection,
                    "shard_ids": [sid],
                    "source_data_node": grpc_addr(src),
                    "copy_ecx_file": not has_vid,
                },
            )
            env.vs_call(
                grpc_addr(dst),
                "VolumeEcShardsMount",
                {"volume_id": vid, "collection": collection, "shard_ids": [sid]},
            )
            env.vs_call(
                grpc_addr(src),
                "VolumeEcShardsDelete",
                {"volume_id": vid, "collection": collection, "shard_ids": [sid]},
            )
            dst.setdefault("ec_shards", []).append(
                {"volume_id": vid, "shard_bits": int(ShardBits.from_ids([sid]))}
            )
            w.write(f"evacuate: ec {vid}.{sid} {fl.node} -> {dst['url']}\n")
            moved += 1
    w.write(f"volumeServer.evacuate: {moved} moves\n")


def _referenced_needles(env: CommandEnv, w: TextIO) -> dict[int, set[int]]:
    """vid -> needle ids referenced by the filer namespace, with chunk
    manifests resolved (filechunk_manifest.go analog: a manifest needle
    indexes further chunk needles, all of which are live references)."""
    import json as _json

    from seaweedfs_tpu.storage.file_id import FileId

    fc = env.filer_client()
    refs: dict[int, set[int]] = {}

    def note(fid: str) -> None:
        try:
            f = FileId.parse(fid)
        except ValueError:
            return
        refs.setdefault(f.volume_id, set()).add(f.key)

    def resolve_manifest(fid: str) -> None:
        note(fid)
        try:
            payload = env.client.read(fid)
            for d in _json.loads(payload.decode()):
                if d.get("is_chunk_manifest"):
                    resolve_manifest(d["fid"])
                else:
                    note(d["fid"])
        except Exception as e:  # noqa: BLE001 — unreadable manifest: report, keep going
            w.write(f"volume.fsck: unreadable manifest {fid}: {e}\n")

    def walk(path: str) -> None:
        start = ""
        while True:
            batch = fc.list(path, start_from=start, limit=1024)
            if not batch:
                return
            for e in batch:
                if e.is_directory:
                    walk(e.path)
                    continue
                for c in e.chunks:
                    if c.is_chunk_manifest:
                        resolve_manifest(c.fid)
                    else:
                        note(c.fid)
            start = batch[-1].name

    walk("/")
    return refs


#: orphan ids per VolumeNeedleTs call (matches VolumeNeedleIds paging) — a
#: very large orphan set in one JSON request can exceed gRPC's default 4 MB
#: message cap, making every holder "fail" and sparing all orphans with a
#: misleading in-flight-upload report
_NEEDLE_TS_CHUNK = 65536


def _orphans_after_cutoff(
    env: CommandEnv, holders: list[dict], vid: int, nids: list[int], cutoff_ns: int
) -> tuple[set[int], set[int]]:
    """-> (dated after the cutoff, undatable). A post-cutoff copy on ANY
    replica is enough to spare the needle everywhere (the delete loop hits
    every holder). Needles NO reachable holder could date — every RPC
    covering them failed — are returned separately so the report says
    'holder unreachable' instead of claiming an upload in flight.
    Chunked VolumeNeedleTs calls per holder; pre-ts (v2) needles report 0
    and stay deletable: the cutoff protects in-flight uploads, which land
    on current-version volumes."""
    newest: dict[int, int] = {}
    covered: set[int] = set()
    for h in holders:
        for i in range(0, len(nids), _NEEDLE_TS_CHUNK):
            chunk = nids[i : i + _NEEDLE_TS_CHUNK]
            try:
                resp = env.vs_call(
                    grpc_addr(h),
                    "VolumeNeedleTs",
                    {"volume_id": vid, "needle_ids": chunk},
                )
            except Exception:  # noqa: BLE001 — holder down: others may answer.
                # Fast-fail the holder's REMAINING chunks: a dead holder
                # would otherwise cost one full RPC timeout per chunk
                # (hours on a multi-million orphan set)
                break
            covered.update(chunk)
            for k, ts in resp.get("ts", {}).items():
                nid = int(k)
                newest[nid] = max(newest.get(nid, 0), int(ts or 0))
    fresh = {nid for nid in covered if newest.get(nid, 0) > cutoff_ns}
    return fresh, set(nids) - covered


def do_volume_fsck(args: list[str], env: CommandEnv, w: TextIO) -> None:
    """Cross-check filer chunk references against volume contents
    (command_volume_fsck.go analog): needles no entry references are
    orphans (reclaimable), references with no needle are data loss.
    Report-only unless -reallyDeleteFromVolume. EC volumes are skipped
    (their needles are audited via the .ecx path at ec.encode time)."""
    fl = parse_flags(args, volumeId=0, reallyDeleteFromVolume=False, cutoffTimeAgo=300)
    env.confirm_locked()
    # An upload racing the run (chunks written before the volume scan, filer
    # entry created after the walk) looks exactly like an orphan; the
    # reference guards this with -cutoffTimeAgo [ref: weed/shell/
    # command_volume_fsck.go — mount empty, SURVEY §2.1]. Record the cutoff
    # BEFORE the scan so every needle appended after it is spared.
    cutoff_ns = int((time.time() - max(fl.cutoffTimeAgo, 0)) * 1e9)
    nodes = env.topology_nodes()
    # Scan the volumes BEFORE walking the filer: a file uploaded mid-run
    # then has its needles absent from `stored` (never an orphan, so never
    # purged) and present in `refs` (at worst a false MISSING report).
    # The reverse order would let -reallyDeleteFromVolume destroy a file
    # written between the walk and the scan. Divergent replicas are
    # merged (union) so a needle on ANY holder is never called missing.
    stored: dict[int, dict[int, int]] = {}  # vid -> id -> size
    holders_of: dict[int, list[dict]] = {}
    for n in nodes:
        for v in n.get("volumes", []):
            vid = int(v["id"])
            if fl.volumeId and vid != fl.volumeId:
                continue
            holders_of.setdefault(vid, []).append(n)
            live, _tombs = _needle_ids_of(env, n, vid)
            stored.setdefault(vid, {}).update(live)
    refs = _referenced_needles(env, w)
    # volumes the filer references that the topology no longer serves at
    # all (every holder dead/lost) — the loudest data-loss signal; EC
    # volumes still serve reads through the shard path, so they're present,
    # just unaudited here
    ec_vids = {
        int(e["volume_id"]) for n in nodes for e in n.get("ec_shards", [])
    }
    orphan_count = orphan_bytes = missing_count = 0
    for vid in sorted(set(refs) - set(stored) - ec_vids):
        if fl.volumeId and vid != fl.volumeId:
            continue
        missing_count += len(refs[vid])
        w.write(
            f"volume {vid}: ABSENT from the topology but {len(refs[vid])} "
            f"needles referenced (data loss)\n"
        )
    for vid in sorted(stored):
        have = stored[vid]
        want = refs.get(vid, set())
        orphans = set(have) - want
        missing = want - set(have)
        if orphans:
            # date candidates in BOTH modes so the report an operator sizes
            # a cleanup from agrees with what a purge would actually delete
            fresh, undatable = _orphans_after_cutoff(
                env, holders_of[vid], vid, sorted(orphans), cutoff_ns
            )
            for nid in sorted(fresh):
                w.write(
                    f"volume {vid}: needle {nid:x} appended after the "
                    f"cutoff — spared (likely an upload in flight)\n"
                )
            for nid in sorted(undatable):
                w.write(
                    f"volume {vid}: needle {nid:x} could not be dated "
                    f"(holder unreachable) — spared\n"
                )
            orphans -= fresh | undatable
        if orphans:
            size = sum(have[i] for i in orphans)
            orphan_count += len(orphans)
            orphan_bytes += size
            w.write(
                f"volume {vid}: {len(orphans)} orphan needles ({size} bytes) "
                f"not referenced by any filer entry\n"
            )
            if fl.reallyDeleteFromVolume:
                for nid in sorted(orphans):
                    for h in holders_of[vid]:
                        env.vs_call(
                            grpc_addr(h),
                            "DeleteNeedle",
                            {"fid": f"{vid},{nid:x}00000000"},
                        )
        for nid in sorted(missing):
            missing_count += 1
            w.write(f"volume {vid}: needle {nid:x} referenced but MISSING (data loss)\n")
    verb = "deleted" if fl.reallyDeleteFromVolume else "found"
    w.write(
        f"volume.fsck: {verb} {orphan_count} orphan needles "
        f"({orphan_bytes} bytes), {missing_count} missing references\n"
    )


register(
    ShellCommand(
        "volume.fsck",
        "volume.fsck [-volumeId <id>] [-reallyDeleteFromVolume] "
        "[-cutoffTimeAgo <secs>]\n\tcross-check filer chunk references against "
        "volume needles; report (or purge) orphans older than the cutoff",
        do_volume_fsck,
    )
)


register(
    ShellCommand(
        "volumeServer.evacuate",
        "volumeServer.evacuate -node <url> [-noApply]\n\tmove every volume and EC "
        "shard off a node so it can be retired",
        do_volume_server_evacuate,
    )
)
