"""Cluster-level shell commands — lock / unlock / cluster.check,
mirroring weed/shell/command_lock_unlock.go and command_cluster_check.go
[VERIFY: mount empty; SURVEY.md §3.1 "acquire cluster exclusive lock"]."""

from __future__ import annotations

from typing import TextIO

import grpc

from seaweedfs_tpu.shell import CommandEnv, ShellCommand, register


def do_lock(args: list[str], env: CommandEnv, w: TextIO) -> None:
    env.lock()
    w.write("cluster locked\n")


def do_unlock(args: list[str], env: CommandEnv, w: TextIO) -> None:
    env.unlock()
    w.write("cluster unlocked\n")


register(
    ShellCommand(
        "lock",
        "lock\n\tlease the cluster-wide exclusive admin lock from the master",
        do_lock,
    )
)
register(
    ShellCommand(
        "unlock",
        "unlock\n\trelease the cluster-wide exclusive admin lock",
        do_unlock,
    )
)


def do_cluster_check(args: list[str], env: CommandEnv, w: TextIO) -> None:
    stats = env.master_call("Statistics", {})
    w.write(
        f"master {env.master_address}: {stats.get('node_count')} nodes, "
        f"{stats.get('volume_count')} volumes, "
        f"{stats.get('ec_volume_count')} ec volumes\n"
    )
    ok = bad = 0
    for n in env.topology_nodes():
        host = n["url"].rsplit(":", 1)[0]
        addr = f"{host}:{n['grpc_port']}"
        try:
            # unconditional probe: NOT_FOUND proves the server answered
            env.vs_call(addr, "VolumeStatus", {"volume_id": 0}, timeout=5)
            w.write(f"  node {n['url']}: ok\n")
            ok += 1
        except grpc.RpcError as e:
            if e.code() == grpc.StatusCode.NOT_FOUND:
                w.write(f"  node {n['url']}: ok\n")
                ok += 1
            else:
                w.write(f"  node {n['url']}: UNREACHABLE ({e.code()})\n")
                bad += 1
        except Exception as e:  # noqa: BLE001 — health summary keeps going
            w.write(f"  node {n['url']}: UNREACHABLE ({e})\n")
            bad += 1
    w.write(f"cluster.check: {ok} healthy, {bad} unreachable\n")


register(
    ShellCommand(
        "cluster.check",
        "cluster.check\n\tverify master and volume-server connectivity",
        do_cluster_check,
    )
)


def do_cluster_raft_ps(args: list[str], env: CommandEnv, w: TextIO) -> None:
    """Show raft membership and leadership (cluster.raft.ps analog)."""
    st = env.master_call("RaftListClusterServers", {})
    if not st.get("enabled"):
        w.write(
            f"raft disabled (single master {st.get('leader')}); "
            "term 0, state leader\n"
        )
        return
    w.write(
        f"leader: {st.get('leader')}  term: {st.get('term')}  "
        f"(answered by {env.master_address}, state {st.get('state')})\n"
    )
    for s in st.get("servers", []):
        mark = "*" if s == st.get("leader") else " "
        w.write(f"  {mark} {s}\n")


register(
    ShellCommand(
        "cluster.raft.ps",
        "cluster.raft.ps\n\tshow raft master membership, leader, and term",
        do_cluster_raft_ps,
    )
)


def do_cluster_ps(args: list[str], env: CommandEnv, w: TextIO) -> None:
    """List cluster processes known to the master: masters, volume
    servers, and announced filers (cluster.ps analog)."""
    st = env.master_call("RaftListClusterServers", {})
    for s in st.get("servers", []):
        mark = "*" if s == st.get("leader") else " "
        w.write(f"master {mark} {s}\n")
    for n in env.topology_nodes():
        w.write(
            f"volume server {n['url']} (grpc :{n['grpc_port']}) "
            f"dc={n.get('data_center')} rack={n.get('rack')} "
            f"volumes={len(n.get('volumes', []))} "
            f"ec={len(n.get('ec_shards', []))}\n"
        )
    filers = env.master_call("ListClusterNodes", {}).get("filers", [])
    for f in filers:
        w.write(f"filer {f.get('http_address')} (grpc {f.get('grpc_address')})\n")


register(
    ShellCommand(
        "cluster.ps",
        "cluster.ps\n\tlist masters, volume servers, and filers in the cluster",
        do_cluster_ps,
    )
)
