"""Cluster-level shell commands — lock / unlock / cluster.check,
mirroring weed/shell/command_lock_unlock.go and command_cluster_check.go
[VERIFY: mount empty; SURVEY.md §3.1 "acquire cluster exclusive lock"]."""

from __future__ import annotations

from typing import TextIO

import grpc

from seaweedfs_tpu.shell import CommandEnv, ShellCommand, register


def do_lock(args: list[str], env: CommandEnv, w: TextIO) -> None:
    env.lock()
    w.write("cluster locked\n")


def do_unlock(args: list[str], env: CommandEnv, w: TextIO) -> None:
    env.unlock()
    w.write("cluster unlocked\n")


register(
    ShellCommand(
        "lock",
        "lock\n\tlease the cluster-wide exclusive admin lock from the master",
        do_lock,
    )
)
register(
    ShellCommand(
        "unlock",
        "unlock\n\trelease the cluster-wide exclusive admin lock",
        do_unlock,
    )
)


def do_cluster_check(args: list[str], env: CommandEnv, w: TextIO) -> None:
    stats = env.master_call("Statistics", {})
    w.write(
        f"master {env.master_address}: {stats.get('node_count')} nodes, "
        f"{stats.get('volume_count')} volumes, "
        f"{stats.get('ec_volume_count')} ec volumes\n"
    )
    ok = bad = 0
    for n in env.topology_nodes():
        host = n["url"].rsplit(":", 1)[0]
        addr = f"{host}:{n['grpc_port']}"
        try:
            # unconditional probe: NOT_FOUND proves the server answered
            env.vs_call(addr, "VolumeStatus", {"volume_id": 0}, timeout=5)
            w.write(f"  node {n['url']}: ok\n")
            ok += 1
        except grpc.RpcError as e:
            if e.code() == grpc.StatusCode.NOT_FOUND:
                w.write(f"  node {n['url']}: ok\n")
                ok += 1
            else:
                w.write(f"  node {n['url']}: UNREACHABLE ({e.code()})\n")
                bad += 1
        except Exception as e:  # noqa: BLE001 — health summary keeps going
            w.write(f"  node {n['url']}: UNREACHABLE ({e})\n")
            bad += 1
    w.write(f"cluster.check: {ok} healthy, {bad} unreachable\n")


register(
    ShellCommand(
        "cluster.check",
        "cluster.check\n\tverify master and volume-server connectivity",
        do_cluster_check,
    )
)
