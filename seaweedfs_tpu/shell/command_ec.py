"""EC lifecycle shell commands — ec.encode / ec.rebuild / ec.decode /
ec.balance, mirroring weed/shell/command_ec_encode.go, command_ec_rebuild.go,
command_ec_decode.go, command_ec_balance.go + command_ec_common.go
[VERIFY: mount empty; SURVEY.md §3.1/§3.3]. Fan-out over nodes uses a
thread pool (errgroup analog)."""

from __future__ import annotations

import json
import os
from concurrent import futures
from typing import Optional, TextIO

from seaweedfs_tpu.ec.constants import DATA_SHARDS_COUNT, TOTAL_SHARDS_COUNT
from seaweedfs_tpu.ec.shard_bits import ShardBits
from seaweedfs_tpu.shell import (
    CommandEnv,
    ShellCommand,
    ShellError,
    grpc_addr,
    parse_flags,
    register,
)

_POOL = 8


class EncodeCheckpoint:
    """Persisted ec.encode work-list (SURVEY §5: "encode of 10k volumes
    resumes"): a batch over many volumes survives interruption — the rerun
    skips completed vids. One JSON file, fsync'd after every finished
    volume, keyed by the volume-selection criteria so a checkpoint from a
    different selection is never misapplied.
    [ref: weed/shell/command_ec_encode.go — mount empty; upstream restarts
    from scratch, this is the resume SURVEY §5 calls out as required.]"""

    def __init__(self, path: str, selector: dict):
        self.path = path
        self.selector = selector

    def load_done(self) -> set[int]:
        try:
            with open(self.path, encoding="utf-8") as f:
                data = json.load(f)
        except (OSError, ValueError):
            return set()
        if data.get("selector") != self.selector:
            return set()  # different batch criteria: ignore, will overwrite
        return {int(v) for v in data.get("done", [])}

    def mark_done(self, done: set[int]) -> None:
        tmp = self.path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"selector": self.selector, "done": sorted(done)}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)

    def finish(self) -> None:
        try:
            os.remove(self.path)
        except OSError:
            pass


def _node_ec_load(node: dict) -> int:
    """Total EC shards currently on the node."""
    return sum(
        ShardBits(e.get("shard_bits", 0)).shard_id_count()
        for e in node.get("ec_shards", [])
    )


def _node_shards_of(node: dict, vid: int) -> list[int]:
    for e in node.get("ec_shards", []):
        if int(e.get("volume_id", -1)) == vid:
            return ShardBits(e.get("shard_bits", 0)).shard_ids()
    return []


def _volume_locations(nodes: list[dict], vid: int) -> list[dict]:
    return [n for n in nodes if any(int(v["id"]) == vid for v in n.get("volumes", []))]


def allocate_shards(
    nodes: list[dict],
    total: int = TOTAL_SHARDS_COUNT,
    data_shards: int = DATA_SHARDS_COUNT,
) -> dict[str, list[int]]:
    """Balanced, FAILURE-DOMAIN-CAPPED spread of `total` shard ids over
    nodes — the shared `ec/placement.py` planner: each shard goes to the
    least-loaded node whose rack still has headroom under the
    no-domain-holds-more-than-m cap (the invariant that makes a whole-
    rack loss survivable by construction); on topologies with too few
    racks the cap relaxes minimally instead of failing."""
    if not nodes:
        raise ShellError("no volume servers available")
    from seaweedfs_tpu.ec import placement
    from seaweedfs_tpu.utils import config as _config

    return placement.plan_spread(
        nodes,
        total,
        max(1, total - data_shards),
        cap_override=int(_config.env("WEEDTPU_PLACEMENT_MAX_PER_DOMAIN")),
        load_of=_node_ec_load,
    )


def _parallel(work: list) -> None:
    """Run thunks concurrently, re-raising the first failure."""
    if not work:
        return
    with futures.ThreadPoolExecutor(max_workers=_POOL) as pool:
        for f in [pool.submit(t) for t in work]:
            f.result()


# -- ec.encode ---------------------------------------------------------------


def _do_ec_encode(
    env: CommandEnv,
    nodes: list[dict],
    vid: int,
    collection: str,
    w: TextIO,
    large_block_size: int = 0,
    small_block_size: int = 0,
    inline: bool = False,
) -> None:
    locations = _volume_locations(nodes, vid)
    if not locations:
        raise ShellError(f"volume {vid} not found on any node")
    # 1. freeze writes on every replica (SURVEY.md §3.1); roll the freeze
    # back if anything later fails, or the volume is stuck readonly forever
    for loc in locations:
        env.vs_call(grpc_addr(loc), "VolumeMarkReadonly", {"volume_id": vid})
    try:
        _encode_spread_cutover(
            env, nodes, locations, vid, collection, w, large_block_size,
            small_block_size, inline,
        )
    except Exception:
        for loc in locations:
            try:
                env.vs_call(grpc_addr(loc), "VolumeMarkWritable", {"volume_id": vid})
            except Exception:  # noqa: BLE001 — best-effort rollback
                pass
        raise


def _encode_spread_cutover(
    env: CommandEnv,
    nodes: list[dict],
    locations: list[dict],
    vid: int,
    collection: str,
    w: TextIO,
    large_block_size: int,
    small_block_size: int,
    inline: bool = False,
) -> None:
    # 2. generate all 14 shards + .ecx on the first replica holder
    # (-inline: finalize from the server's encode-on-write stripe state —
    # byte-identical shards, the encode already amortized into ingest;
    # the server falls back to the warm conversion when no usable inline
    # state exists and reports which path ran)
    source = locations[0]
    src_addr = grpc_addr(source)
    gen_req = {"volume_id": vid, "collection": collection}
    if large_block_size:
        gen_req["large_block_size"] = large_block_size
    if small_block_size:
        gen_req["small_block_size"] = small_block_size
    if inline:
        gen_req["inline"] = True
    gen_resp = env.vs_call(src_addr, "VolumeEcShardsGenerate", gen_req)
    gen_mode = gen_resp.get("mode") if inline else None
    # 3. spread: balanced, rack-aware allocation; targets pull from source
    alloc = allocate_shards(nodes)

    def copy_and_mount(node: dict, sids: list[int]):
        def run():
            addr = grpc_addr(node)
            if node["url"] != source["url"]:
                env.vs_call(
                    addr,
                    "VolumeEcShardsCopy",
                    {
                        "volume_id": vid,
                        "collection": collection,
                        "shard_ids": sids,
                        "source_data_node": src_addr,
                        "copy_ecx_file": True,
                    },
                )
                env.vs_call(
                    addr,
                    "VolumeEcShardsMount",
                    {"volume_id": vid, "collection": collection, "shard_ids": sids},
                )
            return None

        return run

    _parallel([copy_and_mount(n, sids) for url, sids in alloc.items()
               for n in nodes if n["url"] == url])
    # 4. source keeps only its allocated shards (delete remounts the rest).
    # Single-node clusters keep everything: an empty shard_ids list means
    # "delete ALL" to the RPC, so it must not be sent at all.
    kept = alloc.get(source["url"], [])
    moved = [s for s in range(TOTAL_SHARDS_COUNT) if s not in kept]
    if moved:
        env.vs_call(
            src_addr,
            "VolumeEcShardsDelete",
            {"volume_id": vid, "collection": collection, "shard_ids": moved},
        )
    if kept:
        env.vs_call(
            src_addr,
            "VolumeEcShardsMount",
            {"volume_id": vid, "collection": collection, "shard_ids": kept},
        )
    # 5. drop the original volume + replicas — cut-over complete
    for loc in locations:
        env.vs_call(grpc_addr(loc), "VolumeDelete", {"volume_id": vid})
    mode_note = f" ({gen_mode} encode)" if gen_mode else ""
    w.write(f"ec.encode volume {vid}: spread {_fmt_alloc(alloc)}{mode_note}\n")


def _fmt_alloc(alloc: dict[str, list[int]]) -> str:
    return " ".join(f"{u}={','.join(map(str, s))}" for u, s in sorted(alloc.items()))


def do_ec_encode(args: list[str], env: CommandEnv, w: TextIO) -> None:
    fl = parse_flags(
        args,
        volumeId=0,
        collection="",
        fullPercent=95.0,
        quietFor=0,  # seconds since the last write; 0 disables the filter
        force=False,
        largeBlockSize=0,
        smallBlockSize=0,
        inline=False,  # finalize from encode-on-write state (WEEDTPU_INLINE_EC)
        checkpoint=".ec_encode.checkpoint",
    )
    env.confirm_locked()
    topo = env.volume_list()
    nodes = env.topology_nodes()
    limit = int(topo.get("volume_size_limit", 0)) or 1
    # each volume's real collection comes from the topology, not the flag —
    # the flag only SELECTS volumes
    coll_of: dict[int, str] = {}
    for n in nodes:
        for v in n.get("volumes", []):
            coll_of[int(v["id"])] = v.get("collection", "")
    vids: list[int] = []
    if fl.volumeId:
        if fl.volumeId not in coll_of:
            raise ShellError(f"volume {fl.volumeId} not found on any node")
        vids = [fl.volumeId]
    else:
        import time as _time

        now = _time.time()
        # aggregate across replicas FIRST: the quiet check must see the
        # NEWEST write on any replica — a stale replica's old mtime would
        # otherwise select a volume that is actively taking writes
        sizes: dict[int, int] = {}
        newest: dict[int, int] = {}
        for n in nodes:
            for v in n.get("volumes", []):
                vid = int(v["id"])
                if v.get("collection", "") != fl.collection:
                    continue
                sizes[vid] = max(sizes.get(vid, 0), int(v.get("size", 0)))
                newest[vid] = max(newest.get(vid, 0), int(v.get("last_modified", 0)))
        vids = sorted(
            vid
            for vid, size in sizes.items()
            if (fl.force or size >= limit * fl.fullPercent / 100.0)
            # -quietFor: a volume still taking writes must not be EC-frozen
            # (the reference's default encode safety filter)
            and not (fl.quietFor and now - newest[vid] < fl.quietFor)
        )
    if not vids:
        w.write("ec.encode: no matching volumes\n")
        return
    # batch resume: single -volumeId runs don't checkpoint (nothing to skip)
    ckpt = None
    done: set[int] = set()
    if not fl.volumeId and fl.checkpoint:
        ckpt = EncodeCheckpoint(
            fl.checkpoint,
            {
                "collection": fl.collection,
                "fullPercent": fl.fullPercent,
                "quietFor": fl.quietFor,
                "force": bool(fl.force),
            },
        )
        # no intersection with the current selection: a volume whose
        # cut-over completed may still linger in a stale topology view —
        # skipping it is exactly the point
        done = ckpt.load_done()
        if done:
            w.write(f"ec.encode: resuming, {len(done)} volume(s) already done\n")
    for vid in vids:
        if vid in done:
            w.write(f"ec.encode volume {vid}: skip (checkpointed)\n")
            continue
        _do_ec_encode(
            env,
            nodes,
            vid,
            coll_of[vid],
            w,
            large_block_size=fl.largeBlockSize,
            small_block_size=fl.smallBlockSize,
            inline=bool(fl.inline),
        )
        if ckpt is not None:
            done.add(vid)
            ckpt.mark_done(done)
    if ckpt is not None:
        ckpt.finish()  # batch complete: a future batch starts fresh


register(
    ShellCommand(
        "ec.encode",
        "ec.encode -volumeId <id> | -collection <name> [-fullPercent 95] "
        "[-quietFor <secs>] [-force] [-inline] [-checkpoint <file>]\n"
        "\tencode a volume into 14 EC shards, spread them, delete the original;\n"
        "\tbatch runs checkpoint per-volume progress and resume on rerun;\n"
        "\t-inline finalizes from the server's encode-on-write stripe state\n"
        "\t(WEEDTPU_INLINE_EC=on) instead of re-encoding the sealed .dat —\n"
        "\tbyte-identical shards, warm fallback when no usable inline state",
        do_ec_encode,
    )
)


# -- ec.rebuild --------------------------------------------------------------


def _shard_holders(nodes: list[dict], vid: int) -> dict[int, list[dict]]:
    out: dict[int, list[dict]] = {}
    for n in nodes:
        for sid in _node_shards_of(n, vid):
            out.setdefault(sid, []).append(n)
    return out


def _copy_missing_to(env: CommandEnv, node: dict, vid: int, collection: str,
                     holders: dict[int, list[dict]],
                     only: Optional[set] = None) -> list[int]:
    """Pull every survivor shard `node` lacks onto it (restricted to the
    `only` set when given); returns the shard ids temporarily copied (for
    cleanup)."""
    local = set(_node_shards_of(node, vid))
    by_source: dict[str, list[int]] = {}
    for sid, hs in holders.items():
        if sid in local or (only is not None and sid not in only):
            continue
        src = next((h for h in hs if h["url"] != node["url"]), None)
        if src is None:
            continue
        by_source.setdefault(grpc_addr(src), []).append(sid)
    copied: list[int] = []
    first = not local  # no local shards: also pull the index files
    # Pull from every source in parallel (command_ec_rebuild.go's
    # prepareDataToRecover analog): each source writes disjoint .ecNN files
    # on the rebuilder, and the .ecx/.ecj pull rides exactly one call, so
    # the copies are independent. Wall time = slowest source, not the sum.
    jobs = []
    for src_addr, sids in sorted(by_source.items()):
        jobs.append((src_addr, sids, first))
        first = False
    errs: list[str] = []
    with futures.ThreadPoolExecutor(max_workers=min(_POOL, max(1, len(jobs)))) as pool:
        futs = {
            pool.submit(
                env.vs_call,
                grpc_addr(node),
                "VolumeEcShardsCopy",
                {
                    "volume_id": vid,
                    "collection": collection,
                    "shard_ids": sids,
                    "source_data_node": src_addr,
                    "copy_ecx_file": with_ecx,
                },
            ): (src_addr, sids)
            for src_addr, sids, with_ecx in jobs
        }
        for fut in futures.as_completed(futs):
            src_addr, sids = futs[fut]
            try:
                fut.result()
                copied.extend(sids)
            except Exception as e:  # noqa: BLE001
                errs.append(f"{src_addr}: {e}")
    if errs:
        raise ShellError(f"shard copies failed: {'; '.join(errs)}")
    return copied


def _ec_collections(env: CommandEnv) -> dict[int, str]:
    """vid -> collection, from the master's EC registry."""
    return {
        int(vid): coll
        for vid, coll in env.volume_list().get("ec_collections", {}).items()
    }


def do_ec_rebuild(args: list[str], env: CommandEnv, w: TextIO) -> None:
    fl = parse_flags(args, collection="", remote=False, trace="auto")
    trace_mode = str(fl.trace).strip().lower()
    if trace_mode not in ("on", "off", "auto"):
        raise ShellError(f"-trace must be on|off|auto, got {fl.trace!r}")
    env.confirm_locked()
    nodes = env.topology_nodes()
    colls = _ec_collections(env)
    ec_vids = sorted(
        {int(e["volume_id"]) for n in nodes for e in n.get("ec_shards", [])}
    )
    if fl.collection:
        ec_vids = [v for v in ec_vids if colls.get(v, "") == fl.collection]
    for vid in ec_vids:
        collection = colls.get(vid, "")
        holders = _shard_holders(nodes, vid)
        # rebuilder = node already holding the most shards (fewest copies —
        # or, in -remote mode, the fewest slabs streamed over the network)
        rebuilder = max(nodes, key=lambda n: len(_node_shards_of(n, vid)))
        addr = grpc_addr(rebuilder)
        # geometry-flexible volumes (ec.convert targets) record their own
        # (k, k+m): missing-shard detection over the legacy 14 would never
        # see a lost shard id >= 14 of a 20+4 volume, and the survivor
        # gate would mis-assess 12+3. Old servers report 0 -> legacy.
        k, total = DATA_SHARDS_COUNT, TOTAL_SHARDS_COUNT
        try:
            st = env.vs_call(addr, "VolumeStatus", {"volume_id": vid}, timeout=10)
            k = int(st.get("data_shards") or 0) or k
            total = int(st.get("total_shards") or 0) or total
        except Exception:  # noqa: BLE001 — unknown geometry: legacy bounds
            pass
        missing = [s for s in range(total) if s not in holders]
        if not missing:
            continue
        if len(holders) < k:
            w.write(
                f"ec.rebuild volume {vid}: only {len(holders)} shards survive, "
                f"need {k} — data LOST\n"
            )
            continue
        if fl.remote:
            # distributed path: NO bulk survivor pre-copy. The rebuilder
            # streams survivor input from peer holders while decoding —
            # trace-repair projections when the holders speak them
            # (-trace auto/on), full slabs otherwise — writes +
            # CRC-verifies the missing .ecNN files, and mounts only those.
            resp = env.vs_call(
                addr,
                "VolumeEcShardsRebuild",
                {
                    "volume_id": vid,
                    "collection": collection,
                    "remote": True,
                    "trace_mode": trace_mode,
                },
                timeout=600,
            )
            rebuilt = resp.get("rebuilt_shard_ids", [])
            if rebuilt:
                env.vs_call(
                    addr,
                    "VolumeEcShardsMount",
                    {"volume_id": vid, "collection": collection, "shard_ids": rebuilt},
                )
            detail = ""
            if resp.get("remote_survivors"):
                detail = f" (remote survivors {resp['remote_survivors']}"
                if resp.get("failed_over"):
                    detail += f", failed over {resp['failed_over']}"
                if resp.get("mode"):
                    detail += f", {resp['mode']} mode"
                    if resp.get("wire_bytes") is not None:
                        detail += f" moved {resp['wire_bytes']} bytes"
                    if resp.get("trace_fallback"):
                        detail += f", trace fell back: {resp['trace_fallback']}"
                detail += ")"
            w.write(
                f"ec.rebuild volume {vid}: rebuilt {rebuilt} on "
                f"{rebuilder['url']}{detail}\n"
            )
            continue
        copied = _copy_missing_to(env, rebuilder, vid, collection, holders)
        resp = env.vs_call(
            addr, "VolumeEcShardsRebuild", {"volume_id": vid, "collection": collection}
        )
        rebuilt = resp.get("rebuilt_shard_ids", [])
        # drop the temp survivor copies; delete remounts local = original+rebuilt
        if copied:
            env.vs_call(
                addr,
                "VolumeEcShardsDelete",
                {"volume_id": vid, "collection": collection, "shard_ids": copied},
            )
        else:
            env.vs_call(
                addr,
                "VolumeEcShardsMount",
                {"volume_id": vid, "collection": collection, "shard_ids": rebuilt},
            )
        w.write(f"ec.rebuild volume {vid}: rebuilt {rebuilt} on {rebuilder['url']}\n")


register(
    ShellCommand(
        "ec.rebuild",
        "ec.rebuild [-collection <name>] [-remote] [-trace on|off|auto]\n\tfind "
        "EC volumes with lost shards and reconstruct them on a rebuilder node;\n"
        "\t-remote streams survivors from their holders through the network-\n"
        "\toverlapped rebuild pipeline instead of bulk-copying shard files "
        "first;\n\t-trace (with -remote) controls repair-bandwidth projections: "
        "holders ship\n\tGF-projected rows instead of full slabs (on = wherever "
        "holders support\n\tit, auto = only when it also moves fewer bytes; any "
        "failure falls back\n\tto slabs)",
        do_ec_rebuild,
    )
)


# -- ec.convert --------------------------------------------------------------


def do_ec_convert(args: list[str], env: CommandEnv, w: TextIO) -> None:
    """Re-encode an aging EC volume into a different registered code
    family (geometry) without a decode->re-encode round trip: data blocks
    regroup, new parity is a GF projection of surviving shards, progress
    is journaled crash-resumable, and the old geometry serves reads until
    the verified cut-over. The converting node needs the source data
    shards locally, so missing survivors are pulled first (the ec.decode
    pre-copy discipline); stale old-geometry shards on OTHER nodes are
    deleted after cut-over, leaving the converted volume whole on the
    converter — ec.balance re-spreads it."""
    fl = parse_flags(
        args,
        volumeId=0,
        collection="",
        family="",
        nocutover=False,
    )
    if not fl.family:
        raise ShellError("ec.convert needs -family <registered code family>")
    env.confirm_locked()
    nodes = env.topology_nodes()
    colls = _ec_collections(env)
    ec_vids = sorted(
        {int(e["volume_id"]) for n in nodes for e in n.get("ec_shards", [])}
    )
    if fl.volumeId:
        if fl.volumeId not in ec_vids:
            raise ShellError(f"ec volume {fl.volumeId} not found")
        ec_vids = [fl.volumeId]
    elif fl.collection:
        ec_vids = [v for v in ec_vids if colls.get(v, "") == fl.collection]
    if not ec_vids:
        w.write("ec.convert: no matching EC volumes\n")
        return
    for vid in ec_vids:
        collection = colls.get(vid, "")
        holders = _shard_holders(nodes, vid)
        # converter = the node already holding the most shards (fewest
        # survivor copies before the conversion can read the full stripe)
        converter = max(nodes, key=lambda n: len(_node_shards_of(n, vid)))
        addr = grpc_addr(converter)
        # the conversion reads at most k source shards (all data when
        # healthy; parity only stands in for data shards missing
        # everywhere) — pre-copy exactly that set, not every survivor
        only: Optional[set] = None
        try:
            st = env.vs_call(addr, "VolumeStatus", {"volume_id": vid}, timeout=10)
            k = int(st.get("data_shards") or 0)
        except Exception:  # noqa: BLE001 — unknown geometry: copy all
            k = 0
        if k > 0:
            everywhere = set(holders) | set(_node_shards_of(converter, vid))
            data_have = sorted(s for s in everywhere if s < k)[:k]
            only = set(data_have) | set(
                sorted(s for s in everywhere if s >= k)[
                    : max(0, k - len(data_have))
                ]
            )
        copied = _copy_missing_to(
            env, converter, vid, collection, holders, only=only
        )
        resp = env.vs_call(
            addr,
            "VolumeEcShardsConvert",
            {
                "volume_id": vid,
                "collection": collection,
                "target_family": fl.family,
                "cutover": not fl.nocutover,
            },
            timeout=600,
        )
        if not fl.nocutover and resp.get("mode") != "noop":
            # old-geometry shards elsewhere are stale after cut-over —
            # drop them so lookups stop routing reads at dead layouts
            for n in nodes:
                sids = _node_shards_of(n, vid)
                if n["url"] == converter["url"] or not sids:
                    continue
                env.vs_call(
                    grpc_addr(n),
                    "VolumeEcShardsDelete",
                    {
                        "volume_id": vid,
                        "collection": collection,
                        "shard_ids": sids,
                    },
                )
        elif resp.get("mode") == "noop":
            # a noop where the converter already holds the COMPLETE target
            # set while other nodes still hold shards is the signature of
            # a previous ec.convert dying between its cut-over RPC and
            # this cleanup loop: those leftovers are old-GEOMETRY shards a
            # new-geometry locate must never route a read to. Deleting is
            # not safe to automate from here (a healthy resident volume
            # plus deliberate replica copies looks the same), so surface
            # it loudly with the exact remedy.
            held = set(_node_shards_of(converter, vid)) | set(copied)
            tgt_ids = {int(s) for s in resp.get("shard_ids") or []}
            leftovers = [
                (n["url"], _node_shards_of(n, vid))
                for n in nodes
                if n["url"] != converter["url"] and _node_shards_of(n, vid)
            ]
            if tgt_ids and tgt_ids <= held and leftovers:
                for url, sids in leftovers:
                    w.write(
                        f"ec.convert volume {vid}: WARNING possible stale "
                        f"old-geometry shards {sids} on {url} (interrupted "
                        "post-cutover cleanup?) — verify and remove with "
                        "ec.verify / VolumeEcShardsDelete, then ec.balance\n"
                    )
        w.write(
            f"ec.convert volume {vid}: {resp.get('src_family')} -> "
            f"{resp.get('target_family')} ({resp.get('mode')}) on "
            f"{converter['url']}: read {resp.get('bytes_read')} wrote "
            f"{resp.get('bytes_written')} bytes"
            + (
                f", reconstructed {resp['reconstructed_bytes']} degraded"
                if resp.get("reconstructed_bytes")
                else ""
            )
            + ("" if fl.nocutover else ", cut over")
            + "\n"
        )


register(
    ShellCommand(
        "ec.convert",
        "ec.convert -volumeId <id> | -collection <name> -family <name> "
        "[-nocutover]\n"
        "\tre-encode an EC volume into another registered code family "
        "(geometry)\n\twithout decoding: data blocks regroup, new parity "
        "is a GF projection of\n\tsurviving shards, progress journals "
        "crash-resumable (.ecc), and the old\n\tgeometry keeps serving "
        "until the verified cut-over; -nocutover stages the\n\tconverted "
        "set (<base>.cv.*) and leaves retirement to a later call",
        do_ec_convert,
    )
)


# -- ec.verify ---------------------------------------------------------------


def do_ec_verify(args: list[str], env: CommandEnv, w: TextIO) -> None:
    """CRC-verify EC shards against their .eci records on every holder —
    the control-plane face of the scrubber's math (VolumeEcShardsVerify).
    Read-only by default; -quarantine pulls failing shards from serving
    and hands them to the holders' automatic-repair queues."""
    fl = parse_flags(args, volumeId=0, collection="", quarantine=False)
    if fl.quarantine:
        env.confirm_locked()  # mutates serving state on the holders
    nodes = env.topology_nodes()
    colls = _ec_collections(env)
    ec_vids = sorted(
        {int(e["volume_id"]) for n in nodes for e in n.get("ec_shards", [])}
    )
    if fl.volumeId:
        if fl.volumeId not in ec_vids:
            raise ShellError(f"ec volume {fl.volumeId} not found")
        ec_vids = [fl.volumeId]
    elif fl.collection:
        ec_vids = [v for v in ec_vids if colls.get(v, "") == fl.collection]
    bad_total = 0
    for vid in ec_vids:
        collection = colls.get(vid, "")
        for n in nodes:
            if not _node_shards_of(n, vid):
                continue
            try:
                resp = env.vs_call(
                    grpc_addr(n),
                    "VolumeEcShardsVerify",
                    {
                        "volume_id": vid,
                        "collection": collection,
                        "quarantine": bool(fl.quarantine),
                    },
                    timeout=600,  # a full-volume CRC pass, not a ping
                )
            except Exception as e:  # noqa: BLE001 — report, keep verifying
                w.write(f"ec.verify volume {vid} @{n['url']}: ERROR {e}\n")
                bad_total += 1
                continue
            verdicts = {
                int(s): v for s, v in (resp.get("verdicts") or {}).items()
            }
            bad = {s: v for s, v in verdicts.items() if v != "ok"}
            bad_total += len(bad)
            line = " ".join(
                f"{s}={verdicts[s]}" for s in sorted(verdicts)
            ) or "(no local shards)"
            if not resp.get("has_crcs"):
                line += " [no .eci CRC record — unverifiable]"
            if resp.get("quarantined"):
                line += f" [quarantined {sorted(resp['quarantined'])} for repair]"
            w.write(f"ec.verify volume {vid} @{n['url']}: {line}\n")
    w.write(
        f"ec.verify: {bad_total} shard(s) failed verification\n"
        if bad_total
        else "ec.verify: all shards verified clean\n"
    )


register(
    ShellCommand(
        "ec.verify",
        "ec.verify [-volumeId <id>] [-collection <name>] [-quarantine]\n"
        "\tCRC-verify every holder's EC shards against the .eci record "
        "(the scrub\n\tmath, on demand) and print per-shard verdicts; "
        "-quarantine also pulls\n\tfailing shards from serving and queues "
        "their automatic trace-repair",
        do_ec_verify,
    )
)


# -- ec.decode ---------------------------------------------------------------


def do_ec_decode(args: list[str], env: CommandEnv, w: TextIO) -> None:
    fl = parse_flags(args, volumeId=0, collection="")
    env.confirm_locked()
    nodes = env.topology_nodes()
    colls = _ec_collections(env)
    ec_vids = sorted(
        {int(e["volume_id"]) for n in nodes for e in n.get("ec_shards", [])}
    )
    if fl.volumeId:
        if fl.volumeId not in ec_vids:
            raise ShellError(f"ec volume {fl.volumeId} not found")
        ec_vids = [fl.volumeId]
    elif fl.collection:
        ec_vids = [v for v in ec_vids if colls.get(v, "") == fl.collection]
    for vid in ec_vids:
        collection = colls.get(vid, "")
        holders = _shard_holders(nodes, vid)
        target = max(nodes, key=lambda n: len(_node_shards_of(n, vid)))
        addr = grpc_addr(target)
        # the volume's recorded geometry, not the legacy 10/14: a
        # converted (12+3, 20+4) volume has a different survivor gate and
        # remnant-shard range (old servers report 0 -> legacy bounds)
        k, total = DATA_SHARDS_COUNT, TOTAL_SHARDS_COUNT
        try:
            st = env.vs_call(addr, "VolumeStatus", {"volume_id": vid}, timeout=10)
            k = int(st.get("data_shards") or 0) or k
            total = int(st.get("total_shards") or 0) or total
        except Exception:  # noqa: BLE001 — unknown geometry: legacy bounds
            pass
        if len(holders) < k:
            w.write(f"ec.decode volume {vid}: insufficient shards — data LOST\n")
            continue
        _copy_missing_to(env, target, vid, collection, holders)
        env.vs_call(
            addr, "VolumeEcShardsToVolume", {"volume_id": vid, "collection": collection}
        )
        # remove EC remnants everywhere (the .dat volume now lives on target)
        for n in nodes:
            if _node_shards_of(n, vid) or n["url"] == target["url"]:
                env.vs_call(
                    grpc_addr(n),
                    "VolumeEcShardsDelete",
                    {
                        "volume_id": vid,
                        "collection": collection,
                        "shard_ids": list(range(total)),
                    },
                )
        w.write(f"ec.decode volume {vid}: restored as normal volume on {target['url']}\n")


register(
    ShellCommand(
        "ec.decode",
        "ec.decode [-volumeId <id>] [-collection <name>]\n\tconvert EC shard sets "
        "back into normal volumes",
        do_ec_decode,
    )
)


# -- ec.balance --------------------------------------------------------------


def pick_balance_move(
    placement: dict[str, dict[int, set]],
    by_url: dict[str, dict],
    heaviest: str,
    lightest: str,
    colls: dict[int, str],
    collection_filter: str,
):
    """Choose which (vid, shard) to move heaviest -> lightest. Among the
    volumes with a movable shard, prefer the one whose shards are most
    CONCENTRATED in the heavy node's rack relative to the light node's —
    the move then also improves rack spread (command_ec_balance.go
    balances racks before nodes). Pure so the ordering is unit-testable.
    Returns (vid, sid) or None."""

    def rack_shards(vid: int, rack: str) -> int:
        return sum(
            len(placement[u].get(vid, ()))
            for u in placement
            if by_url[u]["rack"] == rack
        )

    src_rack = by_url[heaviest]["rack"]
    dst_rack = by_url[lightest]["rack"]
    candidates = []
    for vid, sids in placement[heaviest].items():
        if collection_filter and colls.get(vid, "") != collection_filter:
            continue
        movable = sids - placement[lightest].get(vid, set())
        if not movable:
            continue
        spread_gain = rack_shards(vid, src_rack) - rack_shards(vid, dst_rack)
        candidates.append((-spread_gain, vid, min(movable)))
    if not candidates:
        return None
    _key, vid, sid = min(candidates)
    return vid, sid


def _move_shard(
    env: CommandEnv, src: dict, dst: dict, vid: int, collection: str,
    sid: int, dst_has_vid: bool,
) -> None:
    """One shard migration dst <- src via the copy/mount/delete RPC
    discipline (PR 12's shard-copy machinery)."""
    env.vs_call(
        grpc_addr(dst),
        "VolumeEcShardsCopy",
        {
            "volume_id": vid,
            "collection": collection,
            "shard_ids": [sid],
            "source_data_node": grpc_addr(src),
            "copy_ecx_file": not dst_has_vid,
        },
    )
    env.vs_call(
        grpc_addr(dst),
        "VolumeEcShardsMount",
        {"volume_id": vid, "collection": collection, "shard_ids": [sid]},
    )
    env.vs_call(
        grpc_addr(src),
        "VolumeEcShardsDelete",
        {"volume_id": vid, "collection": collection, "shard_ids": [sid]},
    )


def fix_placement_moves(
    placement_map: dict[str, dict[int, set]],
    by_url: dict[str, dict],
    parity_of,
    cap_override: int = 0,
    only_vids=None,
):
    """Plan the migrations that restore the failure-domain invariant:
    for every (stripe, domain) holding more than m shards, move the
    excess (highest shard ids first) to nodes in domains with headroom,
    least-loaded first. Pure: yields (vid, sid, src_url, dst_url); the
    caller executes every planned move — `placement_map` is mutated AS
    the plan is built, so a caller-side skip would desynchronize the
    map from the cluster (filter with `only_vids` instead)."""
    from seaweedfs_tpu.ec import placement as pl

    moves: list[tuple[int, int, str, str]] = []
    domains = {u: pl.domain_of(n) for u, n in by_url.items()}
    vids = sorted({vid for per in placement_map.values() for vid in per})
    if only_vids is not None:
        vids = [v for v in vids if v in set(only_vids)]
    for vid in vids:
        parity = parity_of(vid)
        cap = pl.max_per_domain(parity, cap_override)
        holders = {}
        for u, per in placement_map.items():
            for s in per.get(vid, ()):
                holders.setdefault(s, []).append(u)
        for dom, sids in pl.stripe_violations(
            holders, domains, parity, cap_override
        ):
            excess = sids[cap:]
            for sid in excess:
                src_url = next(
                    u for u in holders.get(sid, []) if domains[u] == dom
                )

                def dom_count(d: tuple) -> int:
                    return len(
                        {
                            s
                            for u, per in placement_map.items()
                            if domains[u] == d
                            for s in per.get(vid, ())
                        }
                    )

                candidates = [
                    u
                    for u in placement_map
                    if domains[u] != dom
                    and sid not in placement_map[u].get(vid, ())
                    and dom_count(domains[u]) < cap
                ]
                if not candidates:
                    continue  # nowhere legal: reported, not worsened
                dst_url = min(
                    candidates,
                    key=lambda u: (
                        sum(len(s) for s in placement_map[u].values()),
                        u,
                    ),
                )
                moves.append((vid, sid, src_url, dst_url))
                placement_map[src_url][vid].discard(sid)
                placement_map[dst_url].setdefault(vid, set()).add(sid)
    return moves


def do_ec_balance(args: list[str], env: CommandEnv, w: TextIO) -> None:
    fl = parse_flags(args, collection="", fixPlacement=False)
    env.confirm_locked()
    nodes = env.topology_nodes()
    colls = _ec_collections(env)
    if not nodes:
        raise ShellError("no volume servers")
    # live shard map: url -> {vid -> set(sids)}
    placement: dict[str, dict[int, set]] = {
        n["url"]: {
            int(e["volume_id"]): set(ShardBits(e.get("shard_bits", 0)).shard_ids())
            for e in n.get("ec_shards", [])
        }
        for n in nodes
    }
    by_url = {n["url"]: n for n in nodes}

    def load(url: str) -> int:
        return sum(len(s) for s in placement[url].values())

    moves = 0
    if fl.fixPlacement:
        # restore the failure-domain invariant FIRST (a rack holding >m
        # shards of one stripe): correctness moves beat load moves
        def parity_of(vid: int) -> int:
            holders = [
                u for u, per in placement.items() if per.get(vid)
            ]
            for u in holders:
                try:
                    st = env.vs_call(
                        grpc_addr(by_url[u]), "VolumeStatus",
                        {"volume_id": vid}, timeout=10,
                    )
                    total = int(st.get("total_shards") or 0)
                    data = int(st.get("data_shards") or 0)
                    if total and data:
                        return max(1, total - data)
                except Exception:  # noqa: BLE001 — next holder
                    continue
            return TOTAL_SHARDS_COUNT - DATA_SHARDS_COUNT
        from seaweedfs_tpu.utils import config as _config

        planned = fix_placement_moves(
            placement, by_url, parity_of,
            cap_override=int(_config.env("WEEDTPU_PLACEMENT_MAX_PER_DOMAIN")),
            # filter BEFORE planning: the planner mutates `placement` as
            # it plans, so every planned move must actually execute
            only_vids=(
                [v for v in colls if colls.get(v, "") == fl.collection]
                if fl.collection
                else None
            ),
        )
        for vid, sid, src_url, dst_url in planned:
            _move_shard(
                env, by_url[src_url], by_url[dst_url], vid,
                colls.get(vid, ""), sid,
                # placement was already mutated by the planner: "had the
                # volume before this move" = any shard besides sid
                bool(placement[dst_url].get(vid, set()) - {sid}),
            )
            moves += 1
        if planned:
            w.write(
                f"ec.balance: fixed placement with {len(planned)} "
                "domain-cap move(s)\n"
            )
    while True:
        urls = sorted(placement, key=load)
        lightest, heaviest = urls[0], urls[-1]
        if load(heaviest) - load(lightest) <= 1:
            break
        picked = pick_balance_move(
            placement, by_url, heaviest, lightest, colls, fl.collection
        )
        if picked is None:
            break
        vid, sid = picked
        if fl.fixPlacement:
            # the load loop must not re-break the invariant the fix
            # phase just restored: refuse a move that would push the
            # destination's rack past the domain cap (stop balancing —
            # pick would re-propose the same move forever)
            from seaweedfs_tpu.ec import placement as _pl

            domains = {u: _pl.domain_of(n) for u, n in by_url.items()}
            holders: dict[int, list[str]] = {}
            for u, per in placement.items():
                for s in per.get(vid, ()):
                    holders.setdefault(s, []).append(u)
            # model the move: sid leaves heaviest, lands on lightest
            holders[sid] = [
                u for u in holders.get(sid, []) if u != heaviest
            ] + [lightest]
            if _pl.stripe_violations(
                holders, domains, parity_of(vid),
                int(_config.env("WEEDTPU_PLACEMENT_MAX_PER_DOMAIN")),
            ):
                w.write(
                    "ec.balance: stopping — the next load move would "
                    "violate the domain cap\n"
                )
                break
        collection = colls.get(vid, "")
        src, dst = by_url[heaviest], by_url[lightest]
        env.vs_call(
            grpc_addr(dst),
            "VolumeEcShardsCopy",
            {
                "volume_id": vid,
                "collection": collection,
                "shard_ids": [sid],
                "source_data_node": grpc_addr(src),
                "copy_ecx_file": not placement[lightest].get(vid),
            },
        )
        env.vs_call(
            grpc_addr(dst),
            "VolumeEcShardsMount",
            {"volume_id": vid, "collection": collection, "shard_ids": [sid]},
        )
        env.vs_call(
            grpc_addr(src),
            "VolumeEcShardsDelete",
            {"volume_id": vid, "collection": collection, "shard_ids": [sid]},
        )
        placement[heaviest][vid].discard(sid)
        if not placement[heaviest][vid]:
            del placement[heaviest][vid]
        placement[lightest].setdefault(vid, set()).add(sid)
        moves += 1
    w.write(f"ec.balance: moved {moves} shards\n")


register(
    ShellCommand(
        "ec.balance",
        "ec.balance [-collection <name>] [-fixPlacement]\n\teven out EC "
        "shard counts across volume servers; -fixPlacement first migrates "
        "shards\n\tout of failure domains holding more than m shards of a "
        "stripe (the\n\tno-rack-holds->m invariant), via the copy/mount/"
        "delete shard machinery",
        do_ec_balance,
    )
)


# -- ec.trace ----------------------------------------------------------------


def _fetch_json(url: str, timeout: float = 10.0) -> dict:
    import urllib.request

    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read().decode())


def do_ec_trace(args: list[str], env: CommandEnv, w: TextIO) -> None:
    """Pull retained weedtrace span trees from every volume server's
    `/debug/traces` ring and render them slowest-first — the operator
    answer to "WHY was that read slow": per-stage wall times (lookup vs
    fetch vs hedge vs coalesce wait vs decode) for the tail requests the
    ring always keeps. Read-only; no cluster lock."""
    fl = parse_flags(
        args,
        server="",      # substring filter on the node url
        klass="",       # healthy | ec_intact | degraded | put | ...
        kind="",        # http.read | http.write | rpc.server | ...
        minMs=0.0,      # only traces at least this slow
        limit=5,        # per server
        traceId="",     # one specific id (post-incident grep)
    )
    # the master's ring too (master.http roots, its rpc.server
    # continuations) — "cluster-wide" must include every process that
    # retains traces, not just the volume servers
    nodes = [{"url": env.master_address}] + env.topology_nodes()
    if fl.server:
        nodes = [n for n in nodes if fl.server in n["url"]]
    if not nodes:
        raise ShellError("no matching servers")
    from seaweedfs_tpu.obs import trace as trace_obs

    shown = 0
    for n in sorted(nodes, key=lambda n: n["url"]):
        q = f"?limit={1000000 if fl.traceId else int(fl.limit)}"
        if fl.klass:
            q += f"&class={fl.klass}"
        if fl.kind:
            q += f"&kind={fl.kind}"
        if fl.minMs:
            q += f"&min_ms={fl.minMs}"
        try:
            payload = _fetch_json(f"http://{n['url']}/debug/traces{q}")
        except Exception as e:  # noqa: BLE001 — a dead node has no ring
            w.write(f"# {n['url']}: unreachable ({e})\n")
            continue
        traces = payload.get("traces", [])
        if fl.traceId:
            traces = [t for t in traces if t.get("trace_id") == fl.traceId]
        st = payload.get("stats", {})
        w.write(
            f"# {n['url']}: {len(traces)} shown "
            f"(ring kept {st.get('kept', '?')}/{st.get('offered', '?')} "
            f"offered; tracing "
            f"{'on' if payload.get('enabled') else 'OFF'})\n"
        )
        for t in traces:
            w.write(trace_obs.render_trace(t) + "\n")
            shown += 1
    if not shown:
        w.write("ec.trace: no retained traces matched\n")


register(
    ShellCommand(
        "ec.trace",
        "ec.trace [-server <url-substr>] [-klass <class>] [-kind <kind>] "
        "[-minMs <ms>] [-limit <n>] [-traceId <id>]\n"
        "\trender retained weedtrace span trees from the volume servers' "
        "/debug/traces\n\trings, slowest first — per-stage wall times "
        "(lookup/fetch/hedge/coalesce/\n\tdecode) for tail requests; "
        "-traceId finds one specific request cluster-wide",
        do_ec_trace,
    )
)


# -- ec.status ---------------------------------------------------------------


def _scrape_metrics(url: str, timeout: float = 5.0) -> list[tuple[str, dict, float]]:
    """Parse one node's Prometheus /metrics text into
    [(bare_name, labels, value)] — just enough of the exposition format
    for the health summary (no external client on this image)."""
    import re as _re
    import urllib.request

    out: list[tuple[str, dict, float]] = []
    with urllib.request.urlopen(f"http://{url}/metrics", timeout=timeout) as r:
        text = r.read().decode()
    for line in text.splitlines():
        if line.startswith("#") or " " not in line:
            continue
        name_part, _, value = line.rpartition(" ")
        m = _re.match(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?$", name_part)
        if not m:
            continue
        labels = {}
        if m.group(2):
            for pair in _re.findall(r'(\w+)="([^"]*)"', m.group(2)):
                labels[pair[0]] = pair[1]
        try:
            out.append((m.group(1), labels, float(value)))
        except ValueError:
            continue
    return out


def _metric_sum(rows, name: str, **match) -> float:
    return sum(
        v for n, labels, v in rows
        if n == name and all(labels.get(k) == str(val) for k, val in match.items())
    )


def _fleet_risk_lines(env: CommandEnv) -> list[str]:
    """The fleet-risk section of ec.status: the master scheduler's
    redundancy histogram (stripes by shards lost — the "am I about to
    lose data" view), failure-domain violations, and repair queue depth
    / inflight / recent events."""
    try:
        st = env.master_call("RepairStatus", {})
    except Exception as e:  # noqa: BLE001 — old master: no fleet section
        return [f"fleet: unavailable ({e})"]
    hist = st.get("redundancy_histogram") or {}
    hist_s = " ".join(
        f"{k}-lost={hist[k]}" for k in sorted(hist, key=lambda x: int(x))
    ) or "-"
    lines = [
        "fleet: scheduler="
        + ("on" if st.get("enabled") else "off (WEEDTPU_REPAIR=off)")
        + f" queue={st.get('queue_depth', 0)} inflight={st.get('inflight', 0)}"
        + f" stripes[{hist_s}]"
    ]
    batches = st.get("batches") or []
    if batches:
        fused = st.get("fused_volumes_total", 0)
        last = batches[-1]
        lines.append(
            f"fleet: batches={len(batches)} fused_volumes={fused} last["
            f"volumes={last.get('volumes', 0)}"
            f" sigs={last.get('signature_groups', 0)}"
            f" dispatches={last.get('dispatch_groups', 0)}"
            f" wall={last.get('wall_s', 0.0):.2f}s]"
        )
    suspects = st.get("suspects") or []
    if suspects:
        lines.append(f"fleet: suspects={' '.join(suspects)}")
    for v in st.get("violations") or []:
        lines.append(f"fleet: VIOLATION {v}")
    events = st.get("events") or []
    for e in events[-5:]:
        lines.append(
            f"fleet: [{e['seq']}] vid={e['volume_id']} "
            f"missing={e['missing']} {e['state']}"
            + (f" -> {e['target']}" if e.get("target") else "")
            + (f" ({e['detail']})" if e.get("detail") else "")
        )
    return lines


def do_ec_status(args: list[str], env: CommandEnv, w: TextIO) -> None:
    """One-screen cluster health summary: the master's fleet-risk view
    (redundancy histogram, placement violations, repair queue) plus
    per-server quarantined shards (with reasons, from VolumeStatus),
    scrub progress, rebuild/convert inflight (live weedtpu_rpc_inflight
    gauges), the decoded-interval read cache (hit/miss/hit-rate, bytes
    resident, evictions, invalidations), and the codec backend each
    server selected. Read-only; no cluster lock."""
    parse_flags(args)
    nodes = env.topology_nodes()
    if not nodes:
        raise ShellError("no volume servers")
    for line in _fleet_risk_lines(env):
        w.write(line + "\n")
    for n in sorted(nodes, key=lambda n: n["url"]):
        url = n["url"]
        ec_vids = sorted(
            int(e["volume_id"]) for e in n.get("ec_shards", [])
        )
        quarantined: list[str] = []
        for vid in ec_vids:
            try:
                st = env.vs_call(
                    grpc_addr(n), "VolumeStatus", {"volume_id": vid}, timeout=10
                )
            except Exception:  # noqa: BLE001 — racing unmount: skip
                continue
            for s, reason in sorted((st.get("quarantined") or {}).items()):
                quarantined.append(f"{vid}.{int(s):02d}={reason}")
        try:
            rows = _scrape_metrics(url)
        except Exception as e:  # noqa: BLE001 — node HTTP down
            w.write(f"{url}: UNREACHABLE ({e})\n")
            continue
        scrub_mb = _metric_sum(rows, "weedtpu_scrub_bytes_scanned_total") / 1e6
        cycles = int(_metric_sum(rows, "weedtpu_scrub_cycles_total"))
        found = int(_metric_sum(rows, "weedtpu_scrub_corruptions_found_total"))
        repairs_ok = int(_metric_sum(rows, "weedtpu_scrub_repairs_total", result="ok"))
        repairs_fail = int(
            _metric_sum(rows, "weedtpu_scrub_repairs_total", result="failed")
        )
        rebuild_inflight = int(
            _metric_sum(rows, "weedtpu_rpc_inflight", method="VolumeEcShardsRebuild")
        )
        convert_inflight = int(
            _metric_sum(rows, "weedtpu_rpc_inflight", method="VolumeEcShardsConvert")
        )
        rebuilds_done = int(_metric_sum(rows, "weedtpu_ec_rebuild_seconds_count"))
        converts_done = int(_metric_sum(rows, "weedtpu_ec_convert_seconds_count"))
        backends = sorted(
            f"{labels.get('backend')}({labels.get('source')})"
            for name, labels, v in rows
            if name == "weedtpu_ec_backend_selected" and v == 1.0
        )
        # xorsched schedule-cache state (only exported once the server has
        # dispatched through the xorsched path at least once)
        xs_hits = int(_metric_sum(rows, "weedtpu_xorsched_schedule_cache", event="hits"))
        xs_miss = int(_metric_sum(rows, "weedtpu_xorsched_schedule_cache", event="misses"))
        xs_size = int(_metric_sum(rows, "weedtpu_xorsched_schedule_cache", event="size"))
        xs_cap = int(_metric_sum(rows, "weedtpu_xorsched_schedule_cache", event="cap"))
        xs = (
            f" xorsched={xs_hits}hit/{xs_miss}miss({xs_size}/{xs_cap})"
            if xs_hits or xs_miss
            else ""
        )
        # decoded-interval cache: is degraded hot-set traffic actually
        # being served from cache, and is the budget churning (evictions)
        # or being flushed by topology events (invalidations)?
        cache_hits = int(_metric_sum(rows, "weedtpu_read_cache_hits_total"))
        cache_misses = int(_metric_sum(rows, "weedtpu_read_cache_misses_total"))
        cache_mb = _metric_sum(rows, "weedtpu_read_cache_bytes") / 1e6
        cache_evict = int(_metric_sum(rows, "weedtpu_read_cache_evictions_total"))
        cache_inval = int(
            _metric_sum(rows, "weedtpu_read_cache_invalidations_total")
        )
        cache_rate = (
            f"{cache_hits / (cache_hits + cache_misses):.0%}"
            if cache_hits + cache_misses
            else "-"
        )
        w.write(
            f"{url}: ec_volumes={len(ec_vids)} "
            f"quarantined=[{' '.join(quarantined) or '-'}] "
            f"scrub={scrub_mb:.1f}MB/{cycles}cyc found={found} "
            f"repairs={repairs_ok}ok/{repairs_fail}failed "
            f"rebuild={rebuild_inflight}inflight/{rebuilds_done}done "
            f"convert={convert_inflight}inflight/{converts_done}done "
            f"cache={cache_hits}hit/{cache_misses}miss({cache_rate}) "
            f"{cache_mb:.1f}MB evict={cache_evict} inval={cache_inval} "
            f"backend={','.join(backends) or '?'}{xs}\n"
        )


register(
    ShellCommand(
        "ec.status",
        "ec.status\n\tone-screen cluster health: the master's fleet-risk "
        "view (stripes by\n\tremaining redundancy, failure-domain "
        "violations, repair queue/events),\n\tplus per-server quarantined "
        "shards (+reasons), scrub progress, live\n\trebuild/convert "
        "inflight, repair outcomes, the decoded-interval\n\tread-cache "
        "hit rate / footprint / churn, and the selected codec\n\tbackend",
        do_ec_status,
    )
)


# -- ec.backend --------------------------------------------------------------


def do_ec_backend(args: list[str], env: CommandEnv, w: TextIO) -> None:
    """Operator view of the encoder factory's selection audit: which codec
    backend `new_encoder("auto")` picks HERE and why — the evidence file/
    round behind a fused-kernel or mesh promotion, the mesh shape and
    rebuild variant when the pod path is selected, and the reason string
    when conservative defaults hold. Read-only; no cluster lock."""
    parse_flags(args)
    from seaweedfs_tpu.ops.rs_codec import new_encoder

    enc = new_encoder()
    sel = dict(enc.selection)
    sel.pop("mesh", None)  # the nested decision dict is too noisy for a shell line
    w.write(
        "ec.backend: "
        + " ".join(f"{k}={sel[k]}" for k in sorted(sel) if sel[k] is not None)
        + "\n"
    )
    mesh_dec = enc.selection.get("mesh")
    if isinstance(mesh_dec, dict) and enc.backend != "mesh":
        w.write(
            f"ec.backend: mesh not promoted: {mesh_dec.get('reason', 'n/a')}\n"
        )
    if enc.backend in ("numpy", "native", "xorsched"):
        # CPU-floor audit: which of the three host paths serves, the BENCH
        # evidence round behind an xorsched promotion (- when defaults
        # held), the SIMD level the xor executor would run at, and the
        # compiled-schedule LRU state of THIS process
        from seaweedfs_tpu.ops import xorsched

        ci = xorsched.schedule_cache_info()
        w.write(
            "ec.backend: cpu floor: "
            f"path={enc.backend} "
            f"evidence_round={enc.selection.get('evidence_round', '-')} "
            f"xor_simd={xorsched.native_level()} "
            f"sched_cache={ci['hits']}hit/{ci['misses']}miss "
            f"size={ci['size']}/{ci['cap']} evict={ci['evictions']}\n"
        )


register(
    ShellCommand(
        "ec.backend",
        "ec.backend\n\treport the encoder factory's backend selection audit "
        "(evidence file,\n\tmesh shape/evidence round when the pod path is "
        "promoted, and the reason\n\ta conservative default held otherwise)",
        do_ec_backend,
    )
)
