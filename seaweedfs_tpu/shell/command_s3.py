"""S3 bucket shell commands — s3.bucket.list / create / delete, mirroring
weed/shell/command_s3_bucket_*.go [VERIFY: mount empty; SURVEY.md §2.1
"Shell (ops)" row]. Buckets are filer directories under /buckets (the same
layout the S3 gateway serves), so these commands work through the filer
discovered via the master's cluster-node list.
"""

from __future__ import annotations

from typing import TextIO

from seaweedfs_tpu.filer.entry import Entry
from seaweedfs_tpu.shell import (
    CommandEnv,
    ShellCommand,
    ShellError,
    iter_entries,
    parse_flags,
    register,
)

from seaweedfs_tpu.s3api.server import BUCKETS_ROOT, UPLOADS_ROOT  # one layout source


def _valid_bucket(name: str) -> bool:
    return (
        bool(name)
        and "/" not in name
        and not name.startswith(".")
        and name not in (".", "..")
    )


def do_s3_bucket_list(args: list[str], env: CommandEnv, w: TextIO) -> None:
    fc = env.filer_client()
    count = 0
    for e in iter_entries(fc, BUCKETS_ROOT):
        if e.is_directory and not e.name.startswith("."):
            w.write(f"{e.name}\n")
            count += 1
    w.write(f"total {count} buckets\n")


register(
    ShellCommand(
        "s3.bucket.list",
        "s3.bucket.list\n\tlist S3 buckets (filer directories under /buckets)",
        do_s3_bucket_list,
    )
)


def do_s3_bucket_create(args: list[str], env: CommandEnv, w: TextIO) -> None:
    fl = parse_flags(args, name="")
    if not _valid_bucket(fl.name):
        raise ShellError("s3.bucket.create -name <bucket>")
    fc = env.filer_client()
    path = f"{BUCKETS_ROOT}/{fl.name}"
    if fc.lookup(path) is not None:
        raise ShellError(f"bucket {fl.name!r} already exists")
    fc.create(Entry(path=path, is_directory=True))
    w.write(f"created bucket {fl.name}\n")


register(
    ShellCommand(
        "s3.bucket.create",
        "s3.bucket.create -name <bucket>\n\tcreate an S3 bucket",
        do_s3_bucket_create,
    )
)


def do_s3_bucket_delete(args: list[str], env: CommandEnv, w: TextIO) -> None:
    fl = parse_flags(args, name="", force=False)
    if not _valid_bucket(fl.name):
        raise ShellError("s3.bucket.delete -name <bucket> [-force]")
    fc = env.filer_client()
    path = f"{BUCKETS_ROOT}/{fl.name}"
    if fc.lookup(path) is None:
        raise ShellError(f"bucket {fl.name!r} not found")
    if not fl.force and fc.list(path, limit=1):
        raise ShellError(f"bucket {fl.name!r} is not empty; use -force")
    fc.delete(path, recursive=True)
    try:  # staged multipart parts reference this collection's needles
        fc.delete(f"{UPLOADS_ROOT}/{fl.name}", recursive=True)
    except Exception:  # noqa: BLE001 — no staged uploads
        pass
    try:
        dropped = fc.delete_collection(fl.name)
        if dropped:
            w.write(f"dropped {dropped} volumes of collection {fl.name!r}\n")
    except Exception:  # noqa: BLE001 — reclamation best-effort
        pass
    w.write(f"deleted bucket {fl.name}\n")


register(
    ShellCommand(
        "s3.bucket.delete",
        "s3.bucket.delete -name <bucket> [-force]\n\tdelete an S3 bucket "
        "(-force removes a non-empty bucket)",
        do_s3_bucket_delete,
    )
)


def do_s3_clean_uploads(args: list[str], env: CommandEnv, w: TextIO) -> None:
    """Abort multipart uploads older than -timeAgo (s3.clean.uploads
    analog): a crashed client's staged parts otherwise hold needle space
    forever. Age is the NEWEST activity under the staging dir (latest
    part mtime), so an upload still receiving parts is never aborted."""
    import time as _time

    fl = parse_flags(args, timeAgoSeconds=24 * 3600)
    env.confirm_locked()
    fc = env.filer_client()
    cutoff = _time.time() - fl.timeAgoSeconds
    cleaned = kept = 0
    for b in iter_entries(fc, UPLOADS_ROOT):
        if not b.is_directory:
            continue
        for up in iter_entries(fc, b.path):
            if not up.is_directory:
                continue
            newest = up.attributes.mtime
            for part in iter_entries(fc, up.path):
                newest = max(newest, part.attributes.mtime)
            if newest >= cutoff:
                kept += 1
                continue
            fc.delete(up.path, recursive=True)
            w.write(f"aborted stale upload {b.name}/{up.name}\n")
            cleaned += 1
    w.write(f"s3.clean.uploads: {cleaned} aborted, {kept} kept\n")


register(
    ShellCommand(
        "s3.clean.uploads",
        "s3.clean.uploads [-timeAgoSeconds 86400]\n\tabort multipart uploads "
        "staged longer ago than the cutoff",
        do_s3_clean_uploads,
    )
)
