"""Operator shell — mirror of weed/shell (`weed shell` REPL)
[VERIFY: mount empty; SURVEY.md §2.1 "Shell (ops)" row, §3.1/§3.3 call
stacks]. EC lifecycle orchestration lives HERE, not in the master: the
shell drives encode/rebuild/balance over gRPC while holding a
cluster-wide exclusive lock leased from the master
(wdclient/exclusive_locks analog).

Each command is a `ShellCommand(name, help, do)` where
`do(args: list[str], env: CommandEnv, writer)` mirrors the reference's
`Do(args, commandEnv, writer)` signature.
"""

from __future__ import annotations

import shlex
import threading
from dataclasses import dataclass
from typing import Callable, Iterable, Optional, TextIO

import grpc

from seaweedfs_tpu import rpc
from seaweedfs_tpu.cluster.client import MasterClient
from seaweedfs_tpu.pb import MASTER_SERVICE, VOLUME_SERVICE

LOCK_NAME = "admin"
_RENEW_INTERVAL = 10.0


class ShellError(Exception):
    pass


@dataclass
class ShellCommand:
    name: str
    help: str
    do: Callable[[list[str], "CommandEnv", TextIO], None]


_REGISTRY: dict[str, ShellCommand] = {}


def register(cmd: ShellCommand) -> ShellCommand:
    _REGISTRY[cmd.name] = cmd
    return cmd


def commands() -> dict[str, ShellCommand]:
    # import for registration side effects
    from seaweedfs_tpu.shell import command_cluster  # noqa: F401
    from seaweedfs_tpu.shell import command_ec  # noqa: F401
    from seaweedfs_tpu.shell import command_fs  # noqa: F401
    from seaweedfs_tpu.shell import command_mq  # noqa: F401
    from seaweedfs_tpu.shell import command_s3  # noqa: F401
    from seaweedfs_tpu.shell import command_volume  # noqa: F401

    return dict(_REGISTRY)


class CommandEnv:
    """Shared command environment (commandEnv analog): master client, the
    exclusive-lock lease, and per-node gRPC helpers."""

    def __init__(self, master_address: str, client_name: str = "shell"):
        self.master_address = master_address
        self.client = MasterClient(master_address)
        self.client_name = client_name
        self.cwd = "/"  # fs.cd/fs.pwd REPL state; fs.* paths resolve against it
        self._lock_token = 0
        self._renew_stop: Optional[threading.Event] = None
        self._renew_thread: Optional[threading.Thread] = None

    def close(self) -> None:
        if self.is_locked:
            try:
                self.unlock()
            except Exception:  # noqa: BLE001 — master may be gone
                pass
        fc = getattr(self, "_filer_client", None)
        if fc is not None:
            fc.close()
        self.client.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- master helpers ------------------------------------------------------

    def master_call(self, method: str, req: dict, timeout: float = 30) -> dict:
        """Master RPC via MasterClient's single failover/redirect path
        (thread-safe: the lock renewer calls this concurrently)."""
        return self.client.master_call(method, req, timeout=timeout)

    def resolve(self, path: str) -> str:
        """Resolve an fs.* path argument against the REPL's working
        directory (fs.cd analog of the reference's shell navigation)."""
        import posixpath

        if not path.startswith("/"):
            path = posixpath.join(self.cwd, path)
        return posixpath.normpath(path)

    def filer_client(self):
        """FilerClient for a filer discovered through the master's
        cluster-node list (fs.* commands); cached per env."""
        fc = getattr(self, "_filer_client", None)
        if fc is not None:
            return fc
        filers = self.master_call("ListClusterNodes", {}).get("filers", [])
        if not filers:
            raise ShellError("no filer registered with the master")
        from seaweedfs_tpu.filer.client import FilerClient

        self._filer_client = FilerClient(filers[0]["grpc_address"])
        self._filer_http = filers[0]["http_address"]
        return self._filer_client

    def volume_list(self) -> dict:
        return self.master_call("VolumeList", {})

    def topology_nodes(self) -> list[dict]:
        """Flatten VolumeList's dc -> rack -> node tree, annotating each
        node dict with its dc/rack."""
        out = []
        for dc, racks in self.volume_list().get("data_centers", {}).items():
            for rack, nodes in racks.items():
                for nd in nodes:
                    nd = dict(nd)
                    nd["data_center"] = dc
                    nd["rack"] = rack
                    out.append(nd)
        return out

    def vs_call(self, grpc_address: str, method: str, req: dict, timeout: float = 300) -> dict:
        with rpc.RpcClient(grpc_address) as c:
            return c.call(VOLUME_SERVICE, method, req, timeout=timeout)

    # -- exclusive lock (SURVEY.md §3.1 "acquire cluster exclusive lock") ----

    @property
    def is_locked(self) -> bool:
        return self._lock_token != 0

    def confirm_locked(self) -> None:
        if not self.is_locked:
            raise ShellError("lock the cluster first: run `lock`")

    def lock(self) -> None:
        resp = self.master_call(
            "LeaseAdminToken",
            {
                "lock_name": LOCK_NAME,
                "previous_token": self._lock_token,
                "client_name": self.client_name,
            },
        )
        self._lock_token = int(resp["token"])
        # a second `lock` while already locked is a renewal, not a second
        # renew thread
        if self._renew_thread is None or not self._renew_thread.is_alive():
            self._renew_stop = threading.Event()
            self._renew_thread = threading.Thread(target=self._renew_loop, daemon=True)
            self._renew_thread.start()

    def unlock(self) -> None:
        if self._renew_stop is not None:
            self._renew_stop.set()
        token, self._lock_token = self._lock_token, 0
        if token:
            self.master_call(
                "ReleaseAdminToken", {"lock_name": LOCK_NAME, "previous_token": token}
            )

    def _renew_once(self) -> bool:
        """One lease renewal. Returns False — and drops the token, so the
        next confirm_locked() aborts — when the master says someone else
        holds the lock (our lease expired and was stolen)."""
        try:
            resp = self.master_call(
                "LeaseAdminToken",
                {
                    "lock_name": LOCK_NAME,
                    "previous_token": self._lock_token,
                    "client_name": self.client_name,
                },
            )
            # a freshly promoted leader may reissue the token (lock table
            # replication lags by one heartbeat): adopt it, or the next
            # renewal's stale previous_token aborts the running command
            self._lock_token = int(resp.get("token", self._lock_token))
            return True
        except grpc.RpcError as e:
            if e.code() == grpc.StatusCode.FAILED_PRECONDITION:
                self._lock_token = 0  # lock lost — stop pretending we hold it
                return False
            return True  # transient failure: retry next tick (TTL is 30s)
        except Exception:  # noqa: BLE001 — transient; retry next tick
            return True

    def _renew_loop(self) -> None:
        stop = self._renew_stop
        while not stop.wait(_RENEW_INTERVAL):
            if not self._lock_token or not self._renew_once():
                return


# -- argument helpers (flag.FlagSet analog for `-name=value` style) ----------


def grpc_addr(node: dict) -> str:
    """gRPC address of a topology node dict (shared by all commands)."""
    host = node["url"].rsplit(":", 1)[0]
    return f"{host}:{node['grpc_port']}"


def iter_entries(fc, path: str, page: int = 1024):
    """Fully paged filer directory listing (exclusive start_from resume)
    — the one pagination loop every fs/s3 command shares."""
    start = ""
    while True:
        batch = fc.list(path, start_from=start, limit=page)
        if not batch:
            return
        yield from batch
        start = batch[-1].name


def parse_flags(args: Iterable[str], **defaults):
    """Parse `-name value` / `-name=value` flags with typed defaults.
    Returns an attribute namespace; unknown flags raise ShellError."""

    class NS:
        pass

    ns = NS()
    for k, v in defaults.items():
        setattr(ns, k, v)
    it = iter(list(args))
    for tok in it:
        if not tok.startswith("-"):
            raise ShellError(f"unexpected argument {tok!r}")
        body = tok.lstrip("-")
        if "=" in body:
            name, val = body.split("=", 1)
        else:
            name = body
            val = None
        key = name.replace(".", "_").replace("-", "_")
        if key not in defaults:
            raise ShellError(f"unknown flag -{name}")
        default = defaults[key]
        if isinstance(default, bool):
            setattr(ns, key, True if val is None else val.lower() in ("1", "true", "yes"))
            continue
        if val is None:
            try:
                val = next(it)
            except StopIteration:
                raise ShellError(f"flag -{name} needs a value") from None
        if isinstance(default, int):
            setattr(ns, key, int(val))
        elif isinstance(default, float):
            setattr(ns, key, float(val))
        else:
            setattr(ns, key, val)
    return ns


# -- driver ------------------------------------------------------------------


def run_command(env: CommandEnv, line: str, writer: TextIO) -> None:
    """Parse and run one command line; raises ShellError on failure."""
    parts = shlex.split(line.strip())
    if not parts or parts[0].startswith("#"):
        return
    name, args = parts[0], parts[1:]
    cmds = commands()
    if name in ("help", "?"):
        if args and args[0] in cmds:
            writer.write(f"{args[0]}\n\t{cmds[args[0]].help}\n")
        else:
            for c in sorted(cmds):
                writer.write(f"  {c:<28} {cmds[c].help.splitlines()[0]}\n")
        return
    cmd = cmds.get(name)
    if cmd is None:
        raise ShellError(f"unknown command {name!r} (try `help`)")
    # the shell is a trace ROOT: every RPC a command fans out carries
    # this id in its metadata, so one ec.rebuild/ec.convert run can be
    # reconstructed across every server it touched (ec.trace, glog grep)
    from seaweedfs_tpu.obs import trace as _trace

    with _trace.start("shell.command", klass="shell"):
        _trace.annotate(command=name)
        cmd.do(args, env, writer)


def run_script(env: CommandEnv, script: str, writer: TextIO) -> None:
    """Run `;`-separated commands (the `weed shell -c` path)."""
    for line in script.split(";"):
        if line.strip():
            run_command(env, line, writer)


def repl(env: CommandEnv, stdin, writer: TextIO) -> None:
    writer.write(f"seaweedfs_tpu shell — connected to {env.master_address}\n")
    while True:
        writer.write("> ")
        writer.flush()
        line = stdin.readline()
        if not line or line.strip() in ("exit", "quit"):
            return
        try:
            run_command(env, line, writer)
        except (ShellError, rpc.RpcFault) as e:
            writer.write(f"error: {e}\n")
        except Exception as e:  # noqa: BLE001 — REPL survives command crashes
            writer.write(f"error: {type(e).__name__}: {e}\n")
