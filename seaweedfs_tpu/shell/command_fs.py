"""Filesystem shell commands — fs.ls / fs.cat / fs.mkdir / fs.rm /
fs.mv / fs.du / fs.meta.save / fs.meta.load, mirroring
weed/shell/command_fs_*.go [VERIFY: mount empty; SURVEY.md §2.1 "Shell
(ops)" row; fs.meta.save/load are the §5 metadata export/import
checkpoint mechanism].

The filer is discovered through the master's cluster-node list (filers
announce themselves with FilerHeartbeat).
"""

from __future__ import annotations

import json
import time
from typing import TextIO

from seaweedfs_tpu.filer.entry import Entry
from seaweedfs_tpu.shell import (
    CommandEnv,
    ShellCommand,
    ShellError,
    iter_entries,
    register,
)


def _split(args: list[str], bools: set[str] = frozenset(), valued: set[str] = frozenset()):
    """Split `-flag [value]` options from positional paths."""
    flags: dict[str, object] = {b: False for b in bools}
    flags.update({v: "" for v in valued})
    positional: list[str] = []
    it = iter(args)
    for tok in it:
        if not tok.startswith("-"):
            positional.append(tok)
            continue
        name, _, inline = tok.lstrip("-").partition("=")
        if name in bools:
            flags[name] = True
        elif name in valued:
            if inline:
                flags[name] = inline
            else:
                try:
                    flags[name] = next(it)
                except StopIteration:
                    raise ShellError(f"flag -{name} needs a value") from None
        else:
            raise ShellError(f"unknown flag -{name}")
    return flags, positional


def _positional(args: list[str]) -> list[str]:
    return _split(args)[1]


def _rp(env: CommandEnv, paths: list[str]) -> list[str]:
    """Resolve path args against the REPL working directory (fs.cd)."""
    return [env.resolve(p) for p in paths]


def do_fs_ls(args: list[str], env: CommandEnv, w: TextIO) -> None:
    flags, paths = _split(args, bools={"l"})
    paths = _rp(env, paths or ["."])
    fc = env.filer_client()
    for path in paths:
        entries = fc.list(path, limit=10000)
        for e in entries:
            if flags["l"]:
                kind = "d" if e.is_directory else "-"
                w.write(
                    f"{kind} {e.size:>12} "
                    f"{time.strftime('%Y-%m-%d %H:%M', time.localtime(e.attributes.mtime))} "
                    f"{e.name}\n"
                )
            else:
                w.write(e.name + ("/" if e.is_directory else "") + "\n")


register(
    ShellCommand(
        "fs.ls",
        "fs.ls [-l] [path ...]\n\tlist filer directory entries",
        do_fs_ls,
    )
)


def do_fs_cat(args: list[str], env: CommandEnv, w: TextIO) -> None:
    paths = _rp(env, _positional(args))
    if not paths:
        raise ShellError("fs.cat needs a path")
    fc = env.filer_client()
    for path in paths:
        data = fc.read_file(path)
        try:
            w.write(data.decode())
        except UnicodeDecodeError:
            w.write(f"<{len(data)} binary bytes>\n")


register(ShellCommand("fs.cat", "fs.cat <path ...>\n\tprint file contents", do_fs_cat))


def do_fs_mkdir(args: list[str], env: CommandEnv, w: TextIO) -> None:
    paths = _rp(env, _positional(args))
    if not paths:
        raise ShellError("fs.mkdir needs a path")
    fc = env.filer_client()
    for path in paths:
        fc.create(Entry(path=path, is_directory=True))
        w.write(f"created {path}\n")


register(ShellCommand("fs.mkdir", "fs.mkdir <path ...>\n\tcreate directories", do_fs_mkdir))


def do_fs_rm(args: list[str], env: CommandEnv, w: TextIO) -> None:
    flags, paths = _split(args, bools={"r"})
    paths = _rp(env, paths)
    if not paths:
        raise ShellError("fs.rm needs a path")
    fc = env.filer_client()
    for path in paths:
        fc.delete(path, recursive=bool(flags["r"]))
        w.write(f"removed {path}\n")


register(
    ShellCommand(
        "fs.rm", "fs.rm [-r] <path ...>\n\tdelete files/directories", do_fs_rm
    )
)


def do_fs_mv(args: list[str], env: CommandEnv, w: TextIO) -> None:
    paths = _rp(env, _positional(args))
    if len(paths) != 2:
        raise ShellError("fs.mv needs <src> <dst>")
    env.filer_client().rename(paths[0], paths[1])
    w.write(f"moved {paths[0]} -> {paths[1]}\n")


register(ShellCommand("fs.mv", "fs.mv <src> <dst>\n\tmove/rename an entry", do_fs_mv))


def do_fs_du(args: list[str], env: CommandEnv, w: TextIO) -> None:
    paths = _rp(env, _positional(args) or ["."])
    fc = env.filer_client()

    def walk(path: str) -> tuple[int, int]:
        files, size = 0, 0
        for e in iter_entries(fc, path):
            if e.is_directory:
                f2, s2 = walk(e.path)
                files += f2
                size += s2
            else:
                files += 1
                size += e.size
        return files, size

    for path in paths:
        files, size = walk(path)
        w.write(f"{path}: {files} files, {size} bytes\n")


register(ShellCommand("fs.du", "fs.du [path ...]\n\tdisk usage of a subtree", do_fs_du))


def do_fs_meta_save(args: list[str], env: CommandEnv, w: TextIO) -> None:
    """Export filer metadata (entries incl. chunk lists) as JSONL —
    the §5 checkpoint/backup mechanism (fs.meta.save analog)."""
    flags, roots = _split(args, valued={"o"})
    if not flags["o"]:
        raise ShellError("fs.meta.save needs -o <file>")
    roots = _rp(env, roots or ["."])
    fc = env.filer_client()
    count = 0
    with open(flags["o"], "w", encoding="utf-8") as f:

        def walk(path: str) -> None:
            nonlocal count
            for e in iter_entries(fc, path):
                f.write(json.dumps(e.to_dict()) + "\n")
                count += 1
                if e.is_directory:
                    walk(e.path)

        for r in roots:
            walk(r)
    w.write(f"saved {count} entries to {flags['o']}\n")


register(
    ShellCommand(
        "fs.meta.save",
        "fs.meta.save -o <file> [root ...]\n\texport filer metadata as JSONL",
        do_fs_meta_save,
    )
)


def do_fs_meta_load(args: list[str], env: CommandEnv, w: TextIO) -> None:
    """Import metadata saved by fs.meta.save. Entries point at the SAME
    chunk fids — a namespace restore, not a data copy (matching the
    reference's fs.meta.load)."""
    flags, _ = _split(args, valued={"i"})
    if not flags["i"]:
        raise ShellError("fs.meta.load needs -i <file>")
    fc = env.filer_client()
    count = 0
    with open(flags["i"], encoding="utf-8") as f:
        for lineno, line in enumerate(f, start=1):
            line = line.strip()
            if not line:
                continue
            try:
                d = json.loads(line)
            except ValueError:
                # a restore is not a crash-resume: a torn/corrupt dump line
                # must abort loudly, not be skipped — partial restores are
                # worse than failed ones
                raise ShellError(
                    f"corrupt dump line {lineno} in {flags['i']} — "
                    f"restore aborted after {count} entries"
                )
            fc.create(Entry.from_dict(d))
            count += 1
    w.write(f"loaded {count} entries from {flags['i']}\n")


register(
    ShellCommand(
        "fs.meta.load",
        "fs.meta.load -i <file>\n\trestore filer metadata from a fs.meta.save dump",
        do_fs_meta_load,
    )
)


def do_fs_tree(args: list[str], env: CommandEnv, w: TextIO) -> None:
    """Recursive tree view of the namespace (command_fs_tree.go analog)."""
    paths = _rp(env, _positional(args) or ["."])
    fc = env.filer_client()
    dirs = files = 0

    def walk(path: str, indent: str) -> None:
        nonlocal dirs, files
        for e in iter_entries(fc, path):
            w.write(f"{indent}{e.name}{'/' if e.is_directory else ''}\n")
            if e.is_directory:
                dirs += 1
                walk(e.path, indent + "  ")
            else:
                files += 1

    for p in paths:
        w.write(p + "\n")
        walk(p, "  ")
    w.write(f"{dirs} directories, {files} files\n")


register(
    ShellCommand(
        "fs.tree",
        "fs.tree [path ...]\n\trecursively print the namespace tree",
        do_fs_tree,
    )
)


def do_fs_meta_cat(args: list[str], env: CommandEnv, w: TextIO) -> None:
    """Print one entry's full metadata as JSON (fs.meta.cat analog) —
    chunk list, attributes, extended attrs."""
    paths = _rp(env, _positional(args))
    if not paths:
        raise ShellError("fs.meta.cat <path ...>")
    fc = env.filer_client()
    for path in paths:
        e = fc.lookup(path)
        if e is None:
            raise ShellError(f"{path} not found")
        w.write(json.dumps(e.to_dict(), indent=2, sort_keys=True) + "\n")


register(
    ShellCommand(
        "fs.meta.cat",
        "fs.meta.cat <path ...>\n\tprint an entry's metadata (chunks, attributes) as JSON",
        do_fs_meta_cat,
    )
)


def do_fs_configure(args: list[str], env: CommandEnv, w: TextIO) -> None:
    """Per-path storage rules (command_fs_configure.go analog): pin
    collection/replication/TTL/read-only to a namespace prefix. With no
    flags, prints the active rule set."""
    flags, _ = _split(
        args,
        bools={"readOnly", "delete", "apply"},
        valued={"locationPrefix", "collection", "replication", "ttl"},
    )
    fc = env.filer_client()
    if flags["locationPrefix"]:
        # resolve against the REPL cwd like every other fs.* path — a
        # relative prefix would store a rule that never matches anything
        pfx = env.resolve(str(flags["locationPrefix"]))
        if str(flags["locationPrefix"]).endswith("/") and not pfx.endswith("/"):
            pfx += "/"  # normpath strips the trailing slash prefixes rely on
        flags["locationPrefix"] = pfx
    if not flags["locationPrefix"]:
        rules = fc.get_filer_conf()
        if not rules:
            w.write("fs.configure: no rules\n")
        for r in rules:
            w.write(
                f"{r['location_prefix']}: collection={r.get('collection', '')!r} "
                f"replication={r.get('replication', '')!r} ttl={r.get('ttl', '')!r} "
                f"readOnly={bool(r.get('read_only'))}\n"
            )
        return
    if not flags["apply"]:
        verb = "delete rule for" if flags["delete"] else "set rule for"
        w.write(
            f"fs.configure (dry): would {verb} {flags['locationPrefix']} — "
            "re-run with -apply\n"
        )
        return
    rules = fc.set_filer_conf(
        flags["locationPrefix"],
        collection=str(flags["collection"]),
        replication=str(flags["replication"]),
        ttl=str(flags["ttl"]),
        read_only=bool(flags["readOnly"]),
        delete=bool(flags["delete"]),
    )
    w.write(f"fs.configure: {len(rules)} rules active\n")


register(
    ShellCommand(
        "fs.configure",
        "fs.configure [-locationPrefix /path/ [-collection c] [-replication xyz] "
        "[-ttl 7d] [-readOnly] [-delete] -apply]\n\tper-path storage rules; "
        "no flags prints the active rules",
        do_fs_configure,
    )
)


def do_fs_cd(args: list[str], env: CommandEnv, w: TextIO) -> None:
    """Change the REPL working directory (command_fs_cd.go analog);
    subsequent relative fs.* paths resolve against it."""
    paths = _positional(args)
    target = env.resolve(paths[0] if paths else "/")
    fc = env.filer_client()
    if target != "/":
        e = fc.lookup(target)
        if e is None or not e.is_directory:
            raise ShellError(f"{target} is not a directory")
    env.cwd = target
    w.write(f"cwd: {target}\n")


register(ShellCommand("fs.cd", "fs.cd [dir]\n\tchange the shell working directory", do_fs_cd))


def do_fs_pwd(args: list[str], env: CommandEnv, w: TextIO) -> None:
    w.write(env.cwd + "\n")


register(ShellCommand("fs.pwd", "fs.pwd\n\tprint the shell working directory", do_fs_pwd))
