"""Message-queue shell commands — mq.topic.list / mq.topic.configure /
mq.broker.list, mirroring weed/shell/command_mq_topic_*.go [VERIFY: mount
empty; SURVEY.md §2.1 "Messaging" row]. Brokers are discovered through
the master's cluster-node list (they announce with node_type=broker).
"""

from __future__ import annotations

from typing import TextIO

from seaweedfs_tpu.mq.broker import BrokerClient
from seaweedfs_tpu.shell import CommandEnv, ShellCommand, ShellError, parse_flags, register


def _broker_of(env: CommandEnv) -> str:
    brokers = env.master_call("ListClusterNodes", {}).get("brokers", [])
    if not brokers:
        raise ShellError(
            "no mq broker announced to the master (start `seaweedfs_tpu mq.broker`)"
        )
    return brokers[0]["grpc_address"] or brokers[0]["http_address"]


def do_mq_broker_list(args: list[str], env: CommandEnv, w: TextIO) -> None:
    brokers = env.master_call("ListClusterNodes", {}).get("brokers", [])
    for b in brokers:
        w.write(f"broker {b.get('grpc_address') or b.get('http_address')}\n")
    w.write(f"total {len(brokers)} brokers\n")


register(
    ShellCommand(
        "mq.broker.list",
        "mq.broker.list\n\tlist mq brokers announced to the master",
        do_mq_broker_list,
    )
)


def do_mq_topic_list(args: list[str], env: CommandEnv, w: TextIO) -> None:
    fl = parse_flags(args, namespace="default")
    with BrokerClient(_broker_of(env)) as bc:
        topics = bc.list_topics(namespace=fl.namespace)
    for t in topics:
        w.write(
            f"{fl.namespace}/{t['topic']}: {t.get('partition_count', 1)} partitions\n"
        )
    w.write(f"total {len(topics)} topics\n")


register(
    ShellCommand(
        "mq.topic.list",
        "mq.topic.list [-namespace default]\n\tlist topics on the mq broker",
        do_mq_topic_list,
    )
)


def do_mq_topic_configure(args: list[str], env: CommandEnv, w: TextIO) -> None:
    fl = parse_flags(args, namespace="default", topic="", partitions=4)
    if not fl.topic:
        raise ShellError("mq.topic.configure -topic <name> [-partitions 4]")
    with BrokerClient(_broker_of(env)) as bc:
        bc.configure_topic(
            fl.topic, partition_count=fl.partitions, namespace=fl.namespace
        )
    w.write(
        f"mq.topic.configure: {fl.namespace}/{fl.topic} -> {fl.partitions} partitions\n"
    )


register(
    ShellCommand(
        "mq.topic.configure",
        "mq.topic.configure -topic <name> [-namespace default] [-partitions 4]\n"
        "\tcreate or re-partition a topic",
        do_mq_topic_configure,
    )
)
