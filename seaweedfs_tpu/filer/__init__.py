"""Filer — the namespace layer (L5): a directory tree of entries, each a
list of chunks stored on the volume tier. Mirror of weed/filer/ [VERIFY:
mount empty; SURVEY.md §2.1 "Filer" row, §1 L5].

Components:
  entry.py   — Entry / Attributes / FileChunk records (filer.proto analogs)
  store.py   — FilerStore interface + memory / sqlite implementations
               (the reference's pluggable leveldb/mysql/... store wall)
  chunks.py  — chunk upload/read against the volume tier, manifests, etags
  filer.py   — Filer core: mkdirs, CRUD, recursive delete, rename,
               metadata event log with subscriptions
  server.py  — FilerServer: HTTP file API + weedtpu.Filer RPC service
"""

from seaweedfs_tpu.filer.entry import Attributes, Entry, FileChunk
from seaweedfs_tpu.filer.filer import Filer, MetaEvent
from seaweedfs_tpu.filer.store import FilerStore, MemoryStore, SqliteStore, make_store
from seaweedfs_tpu.filer.server import FilerServer
from seaweedfs_tpu.filer.client import FilerClient

__all__ = [
    "Attributes",
    "Entry",
    "FileChunk",
    "Filer",
    "MetaEvent",
    "FilerStore",
    "MemoryStore",
    "SqliteStore",
    "make_store",
    "FilerServer",
    "FilerClient",
]
