"""BucketedLogStore — per-bucket store separation, the leveldb3-analog
backend ([ref: weed/filer/leveldb3 — mount empty, SURVEY.md §2.1 "Filer"
row]: upstream's modern default gives every /buckets/<name> subtree its
OWN embedded DB so a bucket drop is a directory unlink, not an
O(entries) scan, and one bucket's write load never shares a log or a
compaction with another's).

Routing: paths under /buckets/<name> (and the bucket directory entry
itself) go to data/<name>/filer.log; everything else — the rest of the
namespace, the KV facet (identities, filer.conf), /buckets itself — to
the default store. Each shard is a full LogFilerStore, so crash
recovery (torn-tail truncation, prefix consistency) and compaction hold
per bucket independently.

Deleting the subtree /buckets/<name> closes and REMOVES the bucket's
store directory wholesale — the upstream O(1) bucket-drop semantics the
S3 gateway's per-bucket collections pair with on the volume tier.
"""

from __future__ import annotations

import os
import re
import shutil
import threading

from seaweedfs_tpu.filer.entry import Entry, normalize_path
from seaweedfs_tpu.filer.store import EntryNotFound, FilerStore

BUCKETS_PREFIX = "/buckets"
_SAFE_BUCKET = re.compile(r"^[A-Za-z0-9._-]{1,255}$")


class BucketedLogStore(FilerStore):
    name = "log3"

    def __init__(self, directory: str):
        self._dir = directory
        from seaweedfs_tpu.filer.logstore import LogFilerStore

        self._mk = LogFilerStore
        os.makedirs(os.path.join(directory, "buckets"), exist_ok=True)
        self._default = self._mk(os.path.join(directory, "default"))
        # /buckets is a REAL entry in the default store (not synthesized):
        # a synthetic find() would make mkdirs skip the insert and the
        # root listing would never show /buckets to namespace walkers
        try:
            self._default.find(BUCKETS_PREFIX)
        except EntryNotFound:
            self._default.insert(Entry(path=BUCKETS_PREFIX, is_directory=True))
        self._lock = threading.Lock()
        self._buckets: dict[str, FilerStore] = {}
        for name in sorted(os.listdir(os.path.join(directory, "buckets"))):
            p = os.path.join(directory, "buckets", name)
            # a stray FILE here must not crash the open: only directories
            # are shards
            if _SAFE_BUCKET.fullmatch(name) and os.path.isdir(p):
                self._buckets[name] = self._mk(p)

    # -- routing --------------------------------------------------------------

    def _bucket_of(self, path: str) -> str:
        """Bucket name when `path` is /buckets/<name>[/...] (with a name
        the per-bucket directory layout can host), else ''."""
        if not path.startswith(BUCKETS_PREFIX + "/"):
            return ""
        name = path[len(BUCKETS_PREFIX) + 1 :].split("/", 1)[0]
        return name if _SAFE_BUCKET.fullmatch(name) else ""

    def _route(self, path: str, create: bool = False) -> FilerStore:
        name = self._bucket_of(path)
        if not name:
            return self._default
        with self._lock:
            st = self._buckets.get(name)
            if st is None:
                if not create:
                    return self._default  # unknown bucket: consistent misses
                st = self._buckets[name] = self._mk(
                    os.path.join(self._dir, "buckets", name)
                )
            return st

    # -- FilerStore -----------------------------------------------------------

    def insert(self, entry: Entry) -> None:
        self._route(entry.path, create=True).insert(entry)

    def update(self, entry: Entry) -> None:
        self._route(entry.path, create=True).update(entry)

    def find(self, path: str) -> Entry:
        path = normalize_path(path)
        return self._route(path).find(path)

    def delete(self, path: str) -> None:
        path = normalize_path(path)
        name = self._bucket_of(path)
        if name and path == f"{BUCKETS_PREFIX}/{name}":
            self._drop_bucket(name)
            return
        self._route(path).delete(path)

    def delete_folder_children(self, path: str) -> None:
        path = normalize_path(path)
        name = self._bucket_of(path)
        if name and path == f"{BUCKETS_PREFIX}/{name}":
            # children live exclusively in the bucket shard: dropping and
            # recreating it is the upstream O(1) bucket wipe. The ROOT
            # entry (and its versioning/policy metadata) must survive a
            # children-only wipe per the FilerStore contract.
            try:
                root = self.find(path)
            except EntryNotFound:
                root = Entry(path=path, is_directory=True)
            self._drop_bucket(name)
            with self._lock:
                st = self._buckets[name] = self._mk(
                    os.path.join(self._dir, "buckets", name)
                )
            st.insert(root)
            return
        if path == BUCKETS_PREFIX:
            with self._lock:
                names = list(self._buckets)
            for n in names:
                self._drop_bucket(n)
        self._route(path).delete_folder_children(path)

    def _drop_bucket(self, name: str) -> None:
        with self._lock:
            self._buckets.pop(name, None)
        # deliberately NOT closing the popped store: lock-free readers may
        # still hold it mid-read, and POSIX keeps unlinked-but-open files
        # readable — a close here would turn their 404s into 500s. The
        # file handles fall with the last reference (refcount/GC).
        shutil.rmtree(os.path.join(self._dir, "buckets", name), ignore_errors=True)
        # the bucket DIRECTORY entry may live in the shard (dropped with
        # it) — make sure the default store holds no stale record either
        try:
            self._default.delete(f"{BUCKETS_PREFIX}/{name}")
        except EntryNotFound:
            pass

    def list(self, dir_path, start_from="", include_start=False, limit=1024, prefix=""):
        dir_path = normalize_path(dir_path)
        if dir_path == BUCKETS_PREFIX:
            # bucket roots come from the shard map (each shard holds its
            # own root entry), non-bucket children from the default store;
            # MERGE FIRST, paginate after — capping either source before
            # the merge would make pages skip entries forever
            with self._lock:
                names = sorted(self._buckets)
            merged = []
            for n in names:
                if prefix and not n.startswith(prefix):
                    continue
                with self._lock:
                    st = self._buckets.get(n)
                if st is None:
                    continue  # raced a bucket drop
                try:
                    merged.append(st.find(f"{BUCKETS_PREFIX}/{n}"))
                except EntryNotFound:
                    merged.append(Entry(path=f"{BUCKETS_PREFIX}/{n}", is_directory=True))
            for e in self._default.list(dir_path, limit=1 << 30, prefix=prefix):
                if not self._bucket_of(e.path):
                    merged.append(e)
            merged.sort(key=lambda e: e.name)
            out = []
            for e in merged:
                if start_from and (
                    e.name < start_from
                    or (e.name == start_from and not include_start)
                ):
                    continue
                out.append(e)
                if len(out) >= limit:
                    break
            return out
        return self._route(dir_path).list(
            dir_path, start_from=start_from, include_start=include_start,
            limit=limit, prefix=prefix,
        )

    # KV facet (identities, filer.conf, mq offsets) is cluster-global
    def kv_put(self, key, value):
        self._default.kv_put(key, value)

    def kv_get(self, key):
        return self._default.kv_get(key)

    def kv_delete(self, key):
        self._default.kv_delete(key)

    def close(self):
        self._default.close()
        with self._lock:
            stores, self._buckets = list(self._buckets.values()), {}
        for st in stores:
            st.close()
