"""FilerClient — RPC client for the weedtpu.Filer service, the analog of
the filer_pb client helpers in weed/pb/filer_pb_helper.go and the
FilerClient wrappers used by mount / s3 / replication [VERIFY: mount
empty; SURVEY.md §2.1].

Gateways running in-process with the FilerServer can skip RPC and use
`server.filer` directly; this client is for separate processes
(mount, filer.sync, mq broker)."""

from __future__ import annotations

import base64
from typing import Iterator, Optional

from seaweedfs_tpu import rpc
from seaweedfs_tpu.filer.entry import Entry
from seaweedfs_tpu.filer.filer import MetaEvent
from seaweedfs_tpu.pb import FILER_SERVICE


class FilerClient:
    def __init__(self, grpc_address: str):
        self._rpc = rpc.RpcClient(grpc_address)

    def close(self) -> None:
        self._rpc.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def lookup(self, path: str) -> Optional[Entry]:
        import grpc as _grpc

        try:
            resp = self._rpc.call(FILER_SERVICE, "LookupDirectoryEntry", {"path": path})
        except _grpc.RpcError as e:
            if e.code() == _grpc.StatusCode.NOT_FOUND:
                return None
            raise
        return Entry.from_dict(resp["entry"])

    def list(
        self,
        directory: str,
        start_from: str = "",
        limit: int = 1024,
        prefix: str = "",
        include_start: bool = False,
    ) -> list[Entry]:
        resp = self._rpc.call(
            FILER_SERVICE,
            "ListEntries",
            {
                "directory": directory,
                "start_from": start_from,
                "inclusive_start_from": include_start,
                "limit": limit,
                "prefix": prefix,
            },
        )
        return [Entry.from_dict(d) for d in resp["entries"]]

    def create(self, entry: Entry, o_excl: bool = False) -> None:
        import grpc as _grpc

        try:
            self._rpc.call(
                FILER_SERVICE, "CreateEntry", {"entry": entry.to_dict(), "o_excl": o_excl}
            )
        except _grpc.RpcError as e:
            if e.code() == _grpc.StatusCode.FAILED_PRECONDITION:
                raise IsADirectoryError(entry.path) from None
            if e.code() == _grpc.StatusCode.ALREADY_EXISTS:
                raise FileExistsError(entry.path) from None
            raise

    def update(self, entry: Entry) -> None:
        self._rpc.call(FILER_SERVICE, "UpdateEntry", {"entry": entry.to_dict()})

    def delete(
        self, path: str, recursive: bool = False, delete_data: bool = True
    ) -> None:
        self._rpc.call(
            FILER_SERVICE,
            "DeleteEntry",
            {"path": path, "is_recursive": recursive, "is_delete_data": delete_data},
        )

    def rename(self, old_path: str, new_path: str) -> None:
        import grpc as _grpc

        try:
            self._rpc.call(
                FILER_SERVICE,
                "AtomicRenameEntry",
                {"old_path": old_path, "new_path": new_path},
            )
        except _grpc.RpcError as e:
            if e.code() == _grpc.StatusCode.FAILED_PRECONDITION:
                raise IsADirectoryError(new_path) from None
            if e.code() == _grpc.StatusCode.NOT_FOUND:
                raise FileNotFoundError(old_path) from None
            raise

    def read_file(self, path: str) -> bytes:
        return b"".join(self._rpc.stream(FILER_SERVICE, "ReadFile", {"path": path}))

    def read_range(self, path: str, offset: int, size: int) -> bytes:
        return b"".join(
            self._rpc.stream(
                FILER_SERVICE,
                "ReadFileRange",
                {"path": path, "offset": offset, "size": size},
            )
        )

    def configuration(self) -> dict:
        return self._rpc.call(FILER_SERVICE, "GetFilerConfiguration", {})

    def kv_get(self, key: str) -> Optional[bytes]:
        import grpc as _grpc

        try:
            resp = self._rpc.call(FILER_SERVICE, "KvGet", {"key": key})
        except _grpc.RpcError as e:
            if e.code() == _grpc.StatusCode.NOT_FOUND:
                return None
            raise
        return base64.b64decode(resp["value"])

    def get_filer_conf(self) -> list[dict]:
        """Per-path storage rules (fs.configure / filer_conf.go analog)."""
        return self._rpc.call(FILER_SERVICE, "GetFilerConf", {}).get("rules", [])

    def set_filer_conf(
        self,
        location_prefix: str,
        collection: str = "",
        replication: str = "",
        ttl: str = "",
        read_only: bool = False,
        delete: bool = False,
    ) -> list[dict]:
        """Upsert (or delete) one per-path rule; returns the full rule set."""
        resp = self._rpc.call(
            FILER_SERVICE,
            "SetFilerConf",
            {
                "location_prefix": location_prefix,
                "collection": collection,
                "replication": replication,
                "ttl": ttl,
                "read_only": read_only,
                "delete": delete,
            },
        )
        return resp.get("rules", [])

    def delete_collection(self, collection: str) -> int:
        """Drop every volume of a collection cluster-wide (via the master);
        returns the number of volume/shard-set drops."""
        resp = self._rpc.call(
            FILER_SERVICE, "DeleteCollection", {"collection": collection}
        )
        return int(resp.get("deleted", 0))

    def kv_put(self, key: str, value: bytes) -> None:
        self._rpc.call(
            FILER_SERVICE, "KvPut", {"key": key, "value": base64.b64encode(value).decode()}
        )

    def subscribe(
        self, since_ns: int = 0, path_prefix: str = "/", max_idle_s: float = 0
    ) -> Iterator[MetaEvent]:
        for d in self._rpc.stream(
            FILER_SERVICE,
            "SubscribeMetadata",
            {"since_ns": since_ns, "path_prefix": path_prefix, "max_idle_s": max_idle_s},
            resp_format="json",
        ):
            yield MetaEvent.from_dict(d)
