"""Filer core — mirror of weed/filer/filer.go, filer_delete_entry.go,
filer_notify.go (metadata event log), meta_aggregator.go subscription
semantics [VERIFY: mount empty; SURVEY.md §2.1 "Filer" row].

The Filer owns a FilerStore and layers on:
  - implicit parent-directory creation (mkdirs on CreateEntry)
  - recursive delete with chunk reclamation on the volume tier
  - atomic rename (subtree move)
  - a metadata event log: every mutation appends a MetaEvent; subscribers
    (replication, mq, mount cache invalidation) tail it from a timestamp.
    Events are kept in a bounded in-memory ring and appended to a JSONL
    file when `log_dir` is set, so `filer.sync` can resume after restart
    (SURVEY.md §5 checkpoint/resume).
"""

from __future__ import annotations

import json
import os
import posixpath
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Iterator, Optional

from seaweedfs_tpu.filer.chunks import ChunkIO
from seaweedfs_tpu.filer.entry import Attributes, Entry, normalize_path
from seaweedfs_tpu.filer.store import EntryNotFound, FilerStore

_META_RING = 8192


from seaweedfs_tpu.filer.filer_conf import path_prefix_match as _prefix_match


@dataclass
class MetaEvent:
    """One namespace mutation (EventNotification analog)."""

    ts_ns: int
    directory: str
    old_entry: Optional[dict]  # Entry dict or None
    new_entry: Optional[dict]

    def to_dict(self) -> dict:
        return {
            "ts_ns": self.ts_ns,
            "directory": self.directory,
            "old_entry": self.old_entry,
            "new_entry": self.new_entry,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "MetaEvent":
        return cls(
            ts_ns=int(d["ts_ns"]),
            directory=d["directory"],
            old_entry=d.get("old_entry"),
            new_entry=d.get("new_entry"),
        )


class Filer:
    def __init__(
        self,
        store: FilerStore,
        chunk_io: Optional[ChunkIO] = None,
        log_dir: str = "",
        notification_queue=None,
    ):
        self.store = store
        self.chunk_io = chunk_io
        # per-path rules (fs.configure / filer_conf.go): enforcement lives
        # HERE, not in the HTTP layer, so every mutation surface (HTTP,
        # gRPC CreateEntry/DeleteEntry/rename, S3, mount) honors read-only
        from seaweedfs_tpu.filer.filer_conf import FilerConf

        self.path_conf = FilerConf()
        self.notification_queue = notification_queue
        # notifications dispatch off-thread: send_message may do I/O and
        # _notify runs under the filer lock on every mutation
        self._notif_buf: deque = deque()
        self._notif_cv = threading.Condition()
        self._notif_stop = threading.Event()
        self._notif_thread: Optional[threading.Thread] = None
        self._lock = threading.RLock()
        self._events: deque[MetaEvent] = deque(maxlen=_META_RING)
        self._event_cv = threading.Condition()
        self._log_file = None
        if log_dir:
            os.makedirs(log_dir, exist_ok=True)
            # weedlint: ignore[open-no-ctx] append-only meta log, lives as long as the filer
            self._log_file = open(
                os.path.join(log_dir, "filer.meta.log"), "a", encoding="utf-8"
            )

    def _notif_loop(self) -> None:
        while True:
            with self._notif_cv:
                while not self._notif_buf:
                    if self._notif_stop.is_set():
                        return
                    self._notif_cv.wait(0.5)
                key, ev = self._notif_buf.popleft()
            q = self.notification_queue
            if q is not None:
                try:
                    q.send_message(key, ev)
                except Exception:  # noqa: BLE001 — never fail writes for it
                    pass

    def close(self) -> None:
        self._notif_stop.set()
        t = self._notif_thread
        if t is not None:
            with self._notif_cv:
                self._notif_cv.notify_all()
            t.join(timeout=2.0)
        if self._log_file:
            self._log_file.close()
            self._log_file = None
        self.store.close()

    # -- events ---------------------------------------------------------------

    def _notify(self, old: Optional[Entry], new: Optional[Entry]) -> None:
        directory = (new or old).dir if (new or old) else "/"
        ev = MetaEvent(
            ts_ns=time.time_ns(),
            directory=directory,
            old_entry=old.to_dict() if old else None,
            new_entry=new.to_dict() if new else None,
        )
        with self._event_cv:
            self._events.append(ev)
            if self._log_file:
                self._log_file.write(json.dumps(ev.to_dict()) + "\n")
                self._log_file.flush()
            self._event_cv.notify_all()
        if self.notification_queue is not None:
            key = (new or old).path if (new or old) else "/"
            with self._notif_cv:
                self._notif_buf.append((key, ev.to_dict()))
                if self._notif_thread is None:
                    self._notif_thread = threading.Thread(
                        target=self._notif_loop, daemon=True
                    )
                    self._notif_thread.start()
                self._notif_cv.notify()

    def subscribe(
        self,
        since_ns: int = 0,
        prefix: str = "/",
        stop: Optional[threading.Event] = None,
        poll_interval: float = 0.2,
        idle_timeout: float = 0.0,
    ) -> Iterator[MetaEvent]:
        """Tail the event log from `since_ns`, blocking for new events
        until `stop` is set (stop=None: return once drained). Catches up
        from the on-disk log when the ring no longer reaches back far
        enough. `idle_timeout` > 0 ends the tail after that many seconds
        without events (bounds server-side streams)."""
        last = since_ns
        last_activity = time.monotonic()
        for ev in self._read_log_since(since_ns):
            if _prefix_match(ev.directory, prefix):
                yield ev
            last = max(last, ev.ts_ns)
        while stop is None or not stop.is_set():
            batch: list[MetaEvent] = []
            with self._event_cv:
                batch = [e for e in self._events if e.ts_ns > last]
                if not batch:
                    self._event_cv.wait(poll_interval)
                    batch = [e for e in self._events if e.ts_ns > last]
            for ev in batch:
                last = max(last, ev.ts_ns)
                if _prefix_match(ev.directory, prefix):
                    yield ev
            if batch:
                last_activity = time.monotonic()
            elif stop is None:
                return  # non-blocking mode: drained
            if idle_timeout and time.monotonic() - last_activity > idle_timeout:
                return

    def _read_log_since(self, since_ns: int) -> list[MetaEvent]:
        with self._event_cv:
            ring = list(self._events)
        # the ring answers only when the subscriber's position falls inside
        # it; further back (ring evicted, or events from a prior process)
        # must come from the on-disk log
        if ring and ring[0].ts_ns <= since_ns:
            return [e for e in ring if e.ts_ns > since_ns]
        if self._log_file is None:
            return [e for e in ring if e.ts_ns > since_ns]
        path = self._log_file.name
        out: list[MetaEvent] = []
        try:
            with open(path, encoding="utf-8") as f:
                for line in f:
                    try:
                        ev = MetaEvent.from_dict(json.loads(line))
                    except (ValueError, KeyError):
                        continue  # torn tail write
                    if ev.ts_ns > since_ns:
                        out.append(ev)
        except OSError:
            return [e for e in ring if e.ts_ns > since_ns]
        return out

    # -- CRUD -----------------------------------------------------------------

    @staticmethod
    def _expired(e: Entry) -> bool:
        ttl = e.attributes.ttl_sec
        return ttl > 0 and not e.is_directory and e.attributes.mtime + ttl < time.time()

    def _reap_expired(self, e: Entry) -> None:
        """TTL'd entries are reaped lazily on access (the reference filer
        does the same on read)."""
        try:
            if e.chunks and self.chunk_io is not None:
                self.chunk_io.delete_chunks(e.chunks)
            self.store.delete(e.path)
            self._notify(e, None)
        except Exception:  # noqa: BLE001 — best-effort; retried next access
            pass

    def find_entry(self, path: str) -> Entry:
        e = self.store.find(path)
        if self._expired(e):
            self._reap_expired(e)
            raise EntryNotFound(path)
        return e

    def exists(self, path: str) -> bool:
        try:
            self.store.find(path)
            return True
        except EntryNotFound:
            return False

    def mkdirs(self, path: str, mode: int = 0o770, _events: Optional[list] = None) -> None:
        """Create parents. `_events` collects (old, new) pairs for deferred
        notification instead of emitting immediately — used by rename,
        whose store transaction may still roll back."""
        path = normalize_path(path)
        if path == "/":
            return
        parts = path.strip("/").split("/")
        cur = ""
        with self._lock:
            for p in parts:
                cur += "/" + p
                try:
                    e = self.store.find(cur)
                    if not e.is_directory:
                        raise NotADirectoryError(cur)
                except EntryNotFound:
                    e = Entry(
                        path=cur,
                        is_directory=True,
                        attributes=Attributes(mtime=time.time(), mode=mode | 0o040000),
                    )
                    self.store.insert(e)
                    if _events is None:
                        self._notify(None, e)
                    else:
                        _events.append((None, e))

    def create_entry(self, entry: Entry, o_excl: bool = False) -> Entry:
        """Insert (or overwrite) an entry; parents are created implicitly,
        like the reference's CreateEntry."""
        self._check_writable(entry.path)
        with self._lock:
            self.mkdirs(entry.dir)
            old = None
            try:
                old = self.store.find(entry.path)
                if o_excl:
                    raise FileExistsError(entry.path)
                if old.is_directory and not entry.is_directory:
                    # replacing a dir with a file would orphan its children
                    raise IsADirectoryError(entry.path)
            except EntryNotFound:
                pass
            if (
                old is not None
                and old.chunks
                and self.chunk_io is not None
                and not entry.is_directory
            ):
                # overwrite: reclaim chunks not carried into the new entry
                kept = {c.fid for c in entry.chunks}
                drop = [c for c in old.chunks if c.fid not in kept]
                if drop:
                    self.chunk_io.delete_chunks(drop)
            self.store.insert(entry)
            self._notify(old, entry)
            return entry

    def _check_writable(self, path: str, subtree: bool = False) -> None:
        """Refuse mutations covered by a read-only fs.configure rule.
        Matches the rule's subtree, its root directory itself (a rule
        '/frozen/' must also protect the entry '/frozen'), and — when
        `subtree` is set (delete/rename, which operate on whole subtrees)
        — any ancestor whose removal would take the protected prefix
        with it."""
        p = path.rstrip("/") or "/"
        for rule in self.path_conf.rules:
            if not rule.read_only:
                continue
            pre = rule.location_prefix
            pre_dir = pre.rstrip("/") or "/"
            # segment-boundary match: '/frozen' must not freeze '/frozen2'
            inside = _prefix_match(p, pre_dir)
            contains = subtree and (
                p == "/" or pre_dir == p or pre_dir.startswith(p + "/")
            )
            if inside or contains:
                raise PermissionError(f"{pre} is read-only (fs.configure)")

    def update_entry(self, entry: Entry) -> Entry:
        self._check_writable(entry.path)
        with self._lock:
            old = self.store.find(entry.path)  # raises if absent
            self.store.update(entry)
            self._notify(old, entry)
            return entry

    def delete_entry(
        self,
        path: str,
        recursive: bool = False,
        ignore_recursive_error: bool = False,
        delete_chunks: bool = True,
    ) -> None:
        """Delete an entry; directories require recursive=True when
        non-empty. Chunk needles are reclaimed on the volume tier."""
        path = normalize_path(path)
        self._check_writable(path, subtree=True)
        with self._lock:
            entry = self.store.find(path)
            if entry.is_directory:
                children = self.store.list(path, limit=2)
                if children and not recursive:
                    raise OSError(f"directory {path} not empty")
                self._delete_tree(path, ignore_recursive_error, delete_chunks)
            elif delete_chunks and entry.chunks and self.chunk_io is not None:
                self.chunk_io.delete_chunks(entry.chunks)
            self.store.delete(path)
            self._notify(entry, None)

    def _delete_tree(self, path: str, ignore_errors: bool, delete_chunks: bool) -> None:
        start = ""
        while True:
            batch = self.store.list(path, start_from=start, limit=256)
            if not batch:
                break
            for e in batch:
                try:
                    if e.is_directory:
                        self._delete_tree(e.path, ignore_errors, delete_chunks)
                    elif delete_chunks and e.chunks and self.chunk_io is not None:
                        self.chunk_io.delete_chunks(e.chunks)
                    self.store.delete(e.path)
                    self._notify(e, None)
                except Exception:  # noqa: BLE001
                    if not ignore_errors:
                        raise
            start = batch[-1].name

    def list_entries(
        self,
        dir_path: str,
        start_from: str = "",
        include_start: bool = False,
        limit: int = 1024,
        prefix: str = "",
    ) -> list[Entry]:
        out = self.store.list(
            dir_path,
            start_from=start_from,
            include_start=include_start,
            limit=limit,
            prefix=prefix,
        )
        live = []
        for e in out:
            if self._expired(e):
                self._reap_expired(e)
            else:
                live.append(e)
        return live

    def walk(self, dir_path: str = "/") -> Iterator[Entry]:
        """Depth-first traversal of the subtree (directories first)."""
        start = ""
        while True:
            batch = self.store.list(dir_path, start_from=start, limit=256)
            if not batch:
                return
            for e in batch:
                yield e
                if e.is_directory:
                    yield from self.walk(e.path)
            start = batch[-1].name

    def rename(self, old_path: str, new_path: str) -> Entry:
        """AtomicRenameEntry analog: move an entry (and its subtree) —
        chunks do not move, only namespace records. Stores with real
        transactions (sqlite) group the whole subtree move atomically: a
        crash mid-rename can never leave half the tree at each path.

        Irreversible side effects are deferred until the transaction
        commits: metadata events would replay phantom renames on
        subscribers after a rollback, and deleting a displaced target's
        chunks inside the txn would resurrect a chunk-less entry on
        rollback."""
        old_path = normalize_path(old_path)
        new_path = normalize_path(new_path)
        self._check_writable(old_path, subtree=True)  # both ends mutate
        self._check_writable(new_path, subtree=True)
        events: list[tuple[Entry, Entry]] = []
        reclaim: list = []
        with self._lock:
            with self.store.transaction():
                entry = self._rename_inner(old_path, new_path, events, reclaim)
            # committed: now the side effects are safe to apply
            for old_copy, moved in events:
                self._notify(old_copy, moved)
            if reclaim and self.chunk_io is not None:
                self.chunk_io.delete_chunks(reclaim)
            return entry

    def _rename_inner(
        self, old_path: str, new_path: str, events: list, reclaim: list
    ) -> Entry:
        """Namespace-only subtree move; collects deferred side effects."""
        entry = self.store.find(old_path)
        try:
            target = self.store.find(new_path)
            if target.is_directory and not entry.is_directory:
                raise IsADirectoryError(new_path)
            if target.chunks:  # overwrite: reclaim AFTER commit
                reclaim.extend(target.chunks)
        except EntryNotFound:
            pass
        self.mkdirs(posixpath.dirname(new_path) or "/", _events=events)
        if entry.is_directory:
            # move children first so events replay consistently
            for child in self.store.list(old_path, limit=1 << 30):
                self._rename_inner(
                    child.path, posixpath.join(new_path, child.name), events, reclaim
                )
        old_copy = Entry.from_dict(entry.to_dict())
        entry.path = new_path
        self.store.insert(entry)
        self.store.delete(old_path)
        events.append((old_copy, entry))
        return entry
