"""FilerStore — the pluggable metadata-store wall, mirror of
weed/filer/filerstore.go and the per-backend subpackages (leveldb2/3,
sqlite, mysql, redis, ... ) [VERIFY: mount empty; SURVEY.md §2.1 "Filer"
row]. This image has no leveldb/redis/sql servers, so the two natural
backends are:

  MemoryStore — dict-of-dirs (the reference's tests use an in-memory store)
  SqliteStore — stdlib sqlite3, matching the reference's sqlite backend
                (weed/filer/sqlite) in role: a durable single-file store

Both implement the same five namespace primitives + a KV facet (the
reference stores its own bookkeeping — e.g. remote-storage mappings —
through FilerStore.KvPut/KvGet).
"""

from __future__ import annotations

import contextlib
import json
import posixpath
import sqlite3
import threading
from typing import Iterator, Optional

from seaweedfs_tpu.filer.entry import Entry, normalize_path


class EntryNotFound(KeyError):
    pass


class FilerStore:
    """Abstract store. Directory listings are lexicographic by name."""

    name = "abstract"

    def insert(self, entry: Entry) -> None:
        raise NotImplementedError

    def update(self, entry: Entry) -> None:
        raise NotImplementedError

    def find(self, path: str) -> Entry:
        raise NotImplementedError

    def delete(self, path: str) -> None:
        raise NotImplementedError

    def delete_folder_children(self, path: str) -> None:
        raise NotImplementedError

    def list(
        self,
        dir_path: str,
        start_from: str = "",
        include_start: bool = False,
        limit: int = 1024,
        prefix: str = "",
    ) -> list[Entry]:
        raise NotImplementedError

    def kv_put(self, key: str, value: bytes) -> None:
        raise NotImplementedError

    def kv_get(self, key: str) -> Optional[bytes]:
        raise NotImplementedError

    def kv_delete(self, key: str) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass

    # -- transactions / batch (filerstore.go BeginTransaction/... analog) ----
    #
    # Default: no-op, matching the reference's non-transactional backends
    # (its memory/redis stores accept Begin/Commit without grouping). Stores
    # with real atomicity (sqlite) override all three.

    def begin_transaction(self) -> None:
        pass

    def commit_transaction(self) -> None:
        pass

    def rollback_transaction(self) -> None:
        pass

    @contextlib.contextmanager
    def transaction(self):
        """`with store.transaction():` — commit on success, rollback on
        exception. Multi-entry operations (rename subtree, batch imports)
        group their writes through this."""
        self.begin_transaction()
        try:
            yield self
        except BaseException:
            self.rollback_transaction()
            raise
        else:
            self.commit_transaction()

    def insert_batch(self, entries: list[Entry]) -> None:
        """Insert many entries atomically where the store supports it."""
        with self.transaction():
            for e in entries:
                self.insert(e)


class MemoryStore(FilerStore):
    name = "memory"

    def __init__(self):
        self._lock = threading.RLock()
        # dir -> {name -> Entry}
        self._dirs: dict[str, dict[str, Entry]] = {"/": {}}
        self._kv: dict[str, bytes] = {}

    def insert(self, entry: Entry) -> None:
        with self._lock:
            self._dirs.setdefault(entry.dir, {})[entry.name] = entry
            if entry.is_directory:
                self._dirs.setdefault(entry.path, {})

    update = insert

    def find(self, path: str) -> Entry:
        path = normalize_path(path)
        if path == "/":
            return Entry(path="/", is_directory=True)
        with self._lock:
            d = self._dirs.get(posixpath.dirname(path) or "/", {})
            e = d.get(posixpath.basename(path))
            if e is None:
                raise EntryNotFound(path)
            return e

    def delete(self, path: str) -> None:
        path = normalize_path(path)
        with self._lock:
            d = self._dirs.get(posixpath.dirname(path) or "/", {})
            d.pop(posixpath.basename(path), None)
            self._dirs.pop(path, None)

    def delete_folder_children(self, path: str) -> None:
        path = normalize_path(path)
        with self._lock:
            for name in list(self._dirs.get(path, {})):
                child = posixpath.join(path, name)
                self.delete_folder_children(child)
                self.delete(child)

    def list(self, dir_path, start_from="", include_start=False, limit=1024, prefix=""):
        dir_path = normalize_path(dir_path)
        with self._lock:
            names = sorted(self._dirs.get(dir_path, {}))
            out = []
            for n in names:
                if prefix and not n.startswith(prefix):
                    continue
                if start_from:
                    if n < start_from or (n == start_from and not include_start):
                        continue
                out.append(self._dirs[dir_path][n])
                if len(out) >= limit:
                    break
            return out

    def kv_put(self, key, value):
        with self._lock:
            self._kv[key] = bytes(value)

    def kv_get(self, key):
        with self._lock:
            return self._kv.get(key)

    def kv_delete(self, key):
        with self._lock:
            self._kv.pop(key, None)


class SqliteStore(FilerStore):
    """Durable store on stdlib sqlite3 (one connection, one writer lock —
    the filer serializes writes through Filer's own locking anyway)."""

    name = "sqlite"

    def __init__(self, db_path: str):
        self._lock = threading.RLock()
        self._txn_depth = 0
        self._conn = sqlite3.connect(db_path, check_same_thread=False)
        with self._lock:
            self._conn.executescript(
                """
                CREATE TABLE IF NOT EXISTS entries (
                    dir  TEXT NOT NULL,
                    name TEXT NOT NULL,
                    meta TEXT NOT NULL,
                    PRIMARY KEY (dir, name)
                );
                CREATE TABLE IF NOT EXISTS kv (
                    k TEXT PRIMARY KEY,
                    v BLOB NOT NULL
                );
                """
            )
            self._conn.commit()

    def _maybe_commit(self) -> None:
        if self._txn_depth == 0:
            self._conn.commit()

    # Transactions HOLD the store's RLock from begin to commit/rollback:
    # sqlite's txn state is connection-global, so without the lock a write
    # from another thread (e.g. a KvPut RPC that bypasses Filer._lock)
    # would silently join — and be rolled back with — this transaction
    # while its caller already saw success. Holding the RLock serializes
    # other writers until the commit; the owning thread re-enters freely.

    def begin_transaction(self) -> None:
        self._lock.acquire()
        self._txn_depth += 1

    def commit_transaction(self) -> None:
        if self._txn_depth == 0:
            return
        self._txn_depth -= 1
        if self._txn_depth == 0:
            self._conn.commit()
        self._lock.release()

    def rollback_transaction(self) -> None:
        if self._txn_depth == 0:
            return
        self._conn.rollback()
        while self._txn_depth:
            self._txn_depth -= 1
            self._lock.release()

    def insert_batch(self, entries) -> None:
        with self._lock, self.transaction():
            self._conn.executemany(
                "INSERT OR REPLACE INTO entries (dir, name, meta) VALUES (?,?,?)",
                [(e.dir, e.name, json.dumps(e.to_dict())) for e in entries],
            )

    def close(self) -> None:
        with self._lock:
            self._conn.close()

    def insert(self, entry: Entry) -> None:
        with self._lock:
            self._conn.execute(
                "INSERT OR REPLACE INTO entries (dir, name, meta) VALUES (?,?,?)",
                (entry.dir, entry.name, json.dumps(entry.to_dict())),
            )
            self._maybe_commit()

    update = insert

    def find(self, path: str) -> Entry:
        path = normalize_path(path)
        if path == "/":
            return Entry(path="/", is_directory=True)
        with self._lock:
            row = self._conn.execute(
                "SELECT meta FROM entries WHERE dir=? AND name=?",
                (posixpath.dirname(path) or "/", posixpath.basename(path)),
            ).fetchone()
        if row is None:
            raise EntryNotFound(path)
        return Entry.from_dict(json.loads(row[0]))

    def delete(self, path: str) -> None:
        path = normalize_path(path)
        with self._lock:
            self._conn.execute(
                "DELETE FROM entries WHERE dir=? AND name=?",
                (posixpath.dirname(path) or "/", posixpath.basename(path)),
            )
            self._maybe_commit()

    def delete_folder_children(self, path: str) -> None:
        path = normalize_path(path)
        like = path.rstrip("/") + "/%" if path != "/" else "/%"
        with self._lock:
            self._conn.execute(
                "DELETE FROM entries WHERE dir=? OR dir LIKE ?", (path, like)
            )
            self._maybe_commit()

    def list(self, dir_path, start_from="", include_start=False, limit=1024, prefix=""):
        dir_path = normalize_path(dir_path)
        q = "SELECT meta FROM entries WHERE dir=?"
        args: list = [dir_path]
        if prefix:
            q += " AND name GLOB ?"
            # escape every GLOB metachar so the prefix matches literally
            escaped = (
                prefix.replace("[", "[[]").replace("*", "[*]").replace("?", "[?]")
            )
            args.append(escaped + "*")
        if start_from:
            q += " AND name >= ?" if include_start else " AND name > ?"
            args.append(start_from)
        q += " ORDER BY name LIMIT ?"
        args.append(limit)
        with self._lock:
            rows = self._conn.execute(q, args).fetchall()
        return [Entry.from_dict(json.loads(r[0])) for r in rows]

    def kv_put(self, key, value):
        with self._lock:
            self._conn.execute(
                "INSERT OR REPLACE INTO kv (k, v) VALUES (?,?)", (key, bytes(value))
            )
            self._maybe_commit()

    def kv_get(self, key):
        with self._lock:
            row = self._conn.execute("SELECT v FROM kv WHERE k=?", (key,)).fetchone()
        return bytes(row[0]) if row else None

    def kv_delete(self, key):
        with self._lock:
            self._conn.execute("DELETE FROM kv WHERE k=?", (key,))
            self._maybe_commit()


def make_store(kind: str = "memory", path: str = "") -> FilerStore:
    """Store factory, the `filer.toml` seam (reference: the [leveldb2] /
    [sqlite] / [mysql] sections of filer.toml select the backend)."""
    if kind == "memory":
        return MemoryStore()
    if kind == "sqlite":
        if not path:
            raise ValueError("sqlite store needs a db path")
        return SqliteStore(path)
    if kind in ("log", "weedkv", "leveldb", "leveldb2"):  # embedded engine
        if not path:
            raise ValueError("log store needs a directory")
        from seaweedfs_tpu.filer.logstore import LogFilerStore

        return LogFilerStore(path)
    if kind in ("log3", "leveldb3"):  # per-bucket store separation
        if not path:
            raise ValueError("log3 store needs a directory")
        from seaweedfs_tpu.filer.bucketstore import BucketedLogStore

        return BucketedLogStore(path)
    raise ValueError(f"unknown filer store {kind!r} (memory|sqlite|log|log3)")
