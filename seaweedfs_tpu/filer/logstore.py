"""LogStore — a from-scratch embedded log-structured KV engine, filling the
role of the reference's leveldb2/leveldb3 filer backends ([ref: weed/filer/
leveldb2 — mount empty, SURVEY.md §2.1 "Filer" row]: a durable embedded
store with no external server). The image ships no leveldb, so this is the
same design point built from primitives:

  on disk     append-only log of CRC-framed records
                [crc32(4) | klen(4) | vlen(4) | key | value]
              vlen == 0xFFFFFFFF is a tombstone. Torn/corrupt tail records
              are truncated at replay, like the needle log (.dat) replay.
  in memory   index: key -> (offset, vlen) into the log + a per-directory
              name set for ordered listings (the memtable analog)
  compaction  when dead bytes exceed half the log, live records are
              rewritten to <log>.compact and atomically swapped — the
              LSM merge collapsed to one level, which is the right size
              for filer metadata (entries are small JSON; the value log
              IS the database)

`LogFilerStore` adapts it to the FilerStore interface: entries live under
`e\\x00<dir>\\x00<name>`, the KV facet under `k\\x00<key>`.
"""

from __future__ import annotations

import json
import os
import posixpath
import struct
import threading
import zlib
from typing import Iterator, Optional

from seaweedfs_tpu.filer.entry import Entry, normalize_path
from seaweedfs_tpu.filer.store import EntryNotFound, FilerStore

_HDR = struct.Struct("<III")  # crc32, klen, vlen
_TOMBSTONE = 0xFFFFFFFF


class LogKv:
    """The raw engine: durable byte-key/byte-value with crash-safe replay."""

    def __init__(self, path: str, compact_ratio: float = 0.5):
        self.path = path
        self.compact_ratio = compact_ratio
        self._lock = threading.RLock()
        self._index: dict[bytes, tuple[int, int]] = {}  # key -> (offset, total_len)
        self._dead_bytes = 0
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._replay()
        # weedlint: ignore[open-no-ctx] store-lifetime append+read handles, closed in close()
        self._f = open(path, "ab")
        self._r = open(path, "rb")  # weedlint: ignore[open-no-ctx] see above

    # -- log format -----------------------------------------------------------

    @staticmethod
    def _frame(key: bytes, value: Optional[bytes]) -> bytes:
        vlen = _TOMBSTONE if value is None else len(value)
        body = key + (value or b"")
        crc = zlib.crc32(_HDR.pack(0, len(key), vlen)[4:] + body)
        return _HDR.pack(crc, len(key), vlen) + body

    def _replay(self) -> None:
        """Rebuild the index from the log; truncate a torn tail in place."""
        if not os.path.exists(self.path):
            return
        good = 0
        with open(self.path, "rb") as f:
            data = f.read()
        pos = 0
        while pos + _HDR.size <= len(data):
            crc, klen, vlen = _HDR.unpack_from(data, pos)
            vbytes = 0 if vlen == _TOMBSTONE else vlen
            end = pos + _HDR.size + klen + vbytes
            if end > len(data):
                break  # torn tail
            body = data[pos + _HDR.size : end]
            if zlib.crc32(_HDR.pack(0, klen, vlen)[4:] + body) != crc:
                break  # corrupt record: everything after is suspect
            key = body[:klen]
            old = self._index.pop(key, None)
            if old is not None:
                self._dead_bytes += old[1]
            if vlen == _TOMBSTONE:
                self._dead_bytes += end - pos  # the tombstone itself is dead
            else:
                self._index[key] = (pos, end - pos)
            good = end
            pos = end
        if good < len(data):
            with open(self.path, "r+b") as f:
                f.truncate(good)

    # -- public API -----------------------------------------------------------

    def put(self, key: bytes, value: bytes) -> None:
        rec = self._frame(key, value)
        with self._lock:
            off = self._f.tell()
            self._f.write(rec)
            self._f.flush()
            old = self._index.get(key)
            if old is not None:
                self._dead_bytes += old[1]
            self._index[key] = (off, len(rec))
            self._maybe_compact()

    def get(self, key: bytes) -> Optional[bytes]:
        with self._lock:
            hit = self._index.get(key)
            if hit is None:
                return None
            off, total = hit
            self._r.seek(off)
            rec = self._r.read(total)
        _, klen, vlen = _HDR.unpack_from(rec, 0)
        return rec[_HDR.size + klen : _HDR.size + klen + vlen]

    def delete(self, key: bytes) -> None:
        with self._lock:
            old = self._index.pop(key, None)
            if old is None:
                return
            rec = self._frame(key, None)
            self._f.write(rec)
            self._f.flush()
            self._dead_bytes += old[1] + len(rec)
            self._maybe_compact()

    def keys(self) -> list[bytes]:
        with self._lock:
            return list(self._index)

    def __contains__(self, key: bytes) -> bool:
        with self._lock:
            return key in self._index

    def __len__(self) -> int:
        with self._lock:
            return len(self._index)

    def close(self) -> None:
        with self._lock:
            self._f.close()
            self._r.close()

    def sync(self) -> None:
        with self._lock:
            self._f.flush()
            os.fsync(self._f.fileno())

    # -- compaction -----------------------------------------------------------

    def _maybe_compact(self) -> None:
        """Caller holds the lock. Rewrite live records when the log is more
        than `compact_ratio` dead (and big enough to bother)."""
        size = self._f.tell()
        if size < 1 << 16 or self._dead_bytes < size * self.compact_ratio:
            return
        self.compact()

    def compact(self) -> None:
        with self._lock:
            tmp = self.path + ".compact"
            new_index: dict[bytes, tuple[int, int]] = {}
            with open(tmp, "wb") as out:
                for key, (off, total) in self._index.items():
                    self._r.seek(off)
                    rec = self._r.read(total)
                    new_index[key] = (out.tell(), total)
                    out.write(rec)
                out.flush()
                os.fsync(out.fileno())
            self._f.close()
            self._r.close()
            os.replace(tmp, self.path)
            # restore a fully usable store BEFORE the durability barrier: a
            # failing dir-fsync must surface the error without leaving
            # closed handles and a stale index behind
            # weedlint: ignore[open-no-ctx] compaction swap reopens the store-lifetime handles
            self._f = open(self.path, "ab")
            self._r = open(self.path, "rb")  # weedlint: ignore[open-no-ctx] see above
            self._index = new_index
            self._dead_bytes = 0
            # the rename itself must survive power loss: fsync the parent
            # directory or the swap may vanish and resurrect pre-compaction
            # state (including data deleted since)
            dfd = os.open(os.path.dirname(self.path) or ".", os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)


class LogFilerStore(FilerStore):
    """FilerStore over LogKv — the leveldb2-analog backend."""

    name = "log"

    _E, _K = b"e", b"k"

    def __init__(self, directory: str):
        self._kvlog = LogKv(os.path.join(directory, "filer.log"))
        self._lock = threading.RLock()
        # dir -> sorted-on-demand name set, rebuilt from the index at open
        self._dirs: dict[str, set[str]] = {"/": set()}
        for key in self._kvlog.keys():
            if key[:1] != self._E:
                continue
            _, d, name = key.split(b"\x00", 2)
            self._dirs.setdefault(d.decode(), set()).add(name.decode())

    @classmethod
    def _ekey(cls, dir_path: str, name: str) -> bytes:
        return b"\x00".join((cls._E, dir_path.encode(), name.encode()))

    def insert(self, entry: Entry) -> None:
        with self._lock:
            self._kvlog.put(
                self._ekey(entry.dir, entry.name),
                json.dumps(entry.to_dict()).encode(),
            )
            self._dirs.setdefault(entry.dir, set()).add(entry.name)
            if entry.is_directory:
                self._dirs.setdefault(entry.path, set())

    update = insert

    def find(self, path: str) -> Entry:
        path = normalize_path(path)
        if path == "/":
            return Entry(path="/", is_directory=True)
        raw = self._kvlog.get(
            self._ekey(posixpath.dirname(path) or "/", posixpath.basename(path))
        )
        if raw is None:
            raise EntryNotFound(path)
        return Entry.from_dict(json.loads(raw.decode()))

    def delete(self, path: str) -> None:
        path = normalize_path(path)
        d, name = posixpath.dirname(path) or "/", posixpath.basename(path)
        with self._lock:
            self._kvlog.delete(self._ekey(d, name))
            self._dirs.get(d, set()).discard(name)
            self._dirs.pop(path, None)

    def delete_folder_children(self, path: str) -> None:
        path = normalize_path(path)
        with self._lock:
            for name in sorted(self._dirs.get(path, set())):
                child = posixpath.join(path, name)
                self.delete_folder_children(child)
                self.delete(child)

    def list(self, dir_path, start_from="", include_start=False, limit=1024, prefix=""):
        dir_path = normalize_path(dir_path)
        with self._lock:
            names = sorted(self._dirs.get(dir_path, set()))
        out = []
        for n in names:
            if prefix and not n.startswith(prefix):
                continue
            if start_from:
                if n < start_from or (n == start_from and not include_start):
                    continue
            try:
                out.append(self.find(posixpath.join(dir_path, n)))
            except EntryNotFound:  # pragma: no cover — index/log raced
                continue
            if len(out) >= limit:
                break
        return out

    def kv_put(self, key, value):
        self._kvlog.put(b"\x00".join((self._K, key.encode())), bytes(value))

    def kv_get(self, key):
        return self._kvlog.get(b"\x00".join((self._K, key.encode())))

    def kv_delete(self, key):
        self._kvlog.delete(b"\x00".join((self._K, key.encode())))

    def close(self):
        self._kvlog.close()
