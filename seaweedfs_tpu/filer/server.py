"""Filer server — mirror of weed/server/filer_server.go + the filer HTTP
handlers (filer_server_handlers_read.go/_write.go) and the weedtpu.Filer
gRPC surface from weed/pb/filer.proto [VERIFY: mount empty; SURVEY.md
§2.1 "Filer" row, §1 L5].

HTTP file API (the data path):
  GET    /path/to/file          -> file bytes (Range: bytes=a-b honored)
  GET    /path/to/dir           -> JSON directory listing
                                   (?limit=&lastFileName=&prefix=)
  PUT    /path/to/file          -> chunked upload via assign+POST
  POST   /path/to/file?mv.from= -> rename
  DELETE /path[?recursive=true] -> delete (+chunk reclamation)

RPC service weedtpu.Filer: LookupDirectoryEntry, ListEntries, CreateEntry,
UpdateEntry, DeleteEntry, AtomicRenameEntry, Statistics, KvGet/KvPut,
SubscribeMetadata (server stream of MetaEvent JSON frames).
"""

from __future__ import annotations

import json
import threading
import urllib.parse
from typing import Optional

import grpc

from seaweedfs_tpu import rpc, stats
from seaweedfs_tpu.utils import httpd
from seaweedfs_tpu.cluster.client import MasterClient
from seaweedfs_tpu.filer.chunks import ChunkIO, DEFAULT_CHUNK_SIZE, etag_of
from seaweedfs_tpu.filer.entry import Attributes, Entry, normalize_path
from seaweedfs_tpu.filer.filer import Filer, MetaEvent
from seaweedfs_tpu.filer.store import EntryNotFound, FilerStore, make_store
from seaweedfs_tpu.pb import FILER_SERVICE
from seaweedfs_tpu.security import tls

import io
import time


class FilerServer:
    def __init__(
        self,
        master_address: str,
        store: Optional[FilerStore] = None,
        port: int = 0,
        grpc_port: int = 0,
        host: str = "127.0.0.1",
        chunk_size: int = DEFAULT_CHUNK_SIZE,
        log_dir: str = "",
        collection: str = "",
        replication: str = "",
        signing_key: Optional[bytes] = None,
        read_signing_key: Optional[bytes] = None,
        chunk_cache_bytes: int = 64 << 20,
    ):
        self.master_address = master_address
        self.master = MasterClient(
            master_address, signing_key=signing_key, read_signing_key=read_signing_key
        )
        from seaweedfs_tpu.utils.chunk_cache import ChunkCache

        # hot-chunk read cache (weed/util/chunk_cache analog): fids are
        # immutable so hits never need validation; deletes evict.
        # chunk_cache_bytes=0 disables it (RAM-constrained deployments).
        cache = ChunkCache(memory_bytes=chunk_cache_bytes) if chunk_cache_bytes else None
        self.chunk_io = ChunkIO(self.master, chunk_size=chunk_size, cache=cache)
        self.filer = Filer(store or make_store("memory"), self.chunk_io, log_dir=log_dir)
        self.collection = collection
        self.replication = replication
        self.host = host
        # per-path storage rules (fs.configure), durable in the store's KV;
        # the live object is Filer.path_conf — enforcement happens in the
        # Filer core so every surface (gRPC, S3, mount) honors it
        from seaweedfs_tpu.filer.filer_conf import CONF_KEY, FilerConf

        try:
            self.filer.path_conf = FilerConf.from_json(
                self.filer.store.kv_get(CONF_KEY)
            )
        except Exception:  # noqa: BLE001 — corrupt conf must not brick startup
            pass

        self._grpc = rpc.RpcServer(port=grpc_port, host=host)
        self._grpc.add_service(self._build_service())
        self.grpc_port = self._grpc.port

        self._http = _ThreadingHTTPServer((host, port), _Handler)
        tls.maybe_wrap_https(self._http)  # data-path HTTPS when configured
        self._http.filer_server = self
        self.port = self._http.server_address[1]
        self._http_thread = threading.Thread(target=self._http.serve_forever, daemon=True)
        self._stop = threading.Event()
        self._announce_thread = threading.Thread(target=self._announce_loop, daemon=True)

    # -- lifecycle -----------------------------------------------------------

    @property
    def filer_conf(self):
        """Alias of Filer.path_conf — one live rule-set object."""
        return self.filer.path_conf

    @property
    def url(self) -> str:
        return f"{self.host}:{self.port}"

    @property
    def grpc_address(self) -> str:
        return f"{self.host}:{self.grpc_port}"

    def start(self) -> None:
        self._grpc.start()
        self._http_thread.start()
        self._announce_thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._http.shutdown()
        self._http.server_close()
        self._grpc.stop()
        self.master.close()
        self.filer.close()

    def _announce_loop(self) -> None:
        """Register with the master cluster-node list so shells/mounts
        can discover filers (master_grpc_server_cluster.go analog)."""
        req = {"http_address": self.url, "grpc_address": self.grpc_address}
        while True:
            try:
                self.master.master_call("FilerHeartbeat", req, timeout=5)
            except Exception:  # noqa: BLE001 — master down; retry
                pass
            if self._stop.wait(5.0):
                return

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()

    # -- upload/read helpers shared by HTTP and gateways ----------------------

    def write_file(
        self,
        path: str,
        reader,
        mime: str = "",
        mode: int = 0o660,
        collection: str = "",
        replication: str = "",
        ttl: str = "",
        extended: Optional[dict] = None,
        o_excl: bool = False,
    ) -> Entry:
        # per-path rules (fs.configure / filer_conf.go): explicit request
        # values win, then the longest-prefix rule, then the per-bucket
        # collection (objects under /buckets/<name>/ land in collection
        # <name>, so deleting a bucket is an O(volumes) collection drop),
        # then server defaults
        rule = self.filer_conf.match(path)
        if rule is not None:
            if rule.read_only:
                raise PermissionError(
                    f"{rule.location_prefix} is read-only (fs.configure)"
                )
            collection = collection or rule.collection
            replication = replication or rule.replication
            ttl = ttl or rule.ttl
        if not collection and path.startswith("/buckets/"):
            segs = path[len("/buckets/"):].split("/")
            # multipart parts stage under /buckets/.uploads/<bucket>/…:
            # they must land in the BUCKET's collection (Complete splices
            # these very fids into the final object) or the per-bucket
            # collection drop would never reclaim multipart objects
            if segs[0] == ".uploads" and len(segs) > 1:
                segs = segs[1:]
            if segs[0] and not segs[0].startswith("."):
                collection = segs[0]
        collection = collection or self.collection
        replication = replication or self.replication
        chunks, size, md5hex = self.chunk_io.upload_stream(
            reader, collection=collection, replication=replication, ttl=ttl
        )
        chunks = self.chunk_io.maybe_manifestize(
            chunks, collection=collection, replication=replication, ttl=ttl
        )
        ttl_sec = 0
        if ttl:
            from seaweedfs_tpu.storage.super_block import TTL

            ttl_sec = TTL.parse(ttl).minutes() * 60
        entry = Entry(
            path=path,
            is_directory=False,
            attributes=Attributes(
                mtime=time.time(),
                mode=mode,
                mime=mime,
                collection=collection,
                replication=replication,
                ttl_sec=ttl_sec,
                md5=md5hex,
                file_size=size,
            ),
            chunks=chunks,
            extended=dict(extended or {}),
        )
        return self.filer.create_entry(entry, o_excl=o_excl)

    def read_file(self, entry: Entry) -> bytes:
        return self.chunk_io.read_all(entry.chunks)

    # -- RPC service ---------------------------------------------------------

    def _build_service(self) -> rpc.Service:
        svc = rpc.Service(FILER_SERVICE)
        add = svc.add
        add("LookupDirectoryEntry", self._rpc_lookup)
        add("ListEntries", self._rpc_list)
        add("CreateEntry", self._rpc_create)
        add("UpdateEntry", self._rpc_update)
        add("DeleteEntry", self._rpc_delete)
        add("AtomicRenameEntry", self._rpc_rename)
        add("Statistics", self._rpc_statistics)
        add("KvGet", self._rpc_kv_get)
        add("KvPut", self._rpc_kv_put)
        add("ReadFile", self._rpc_read_file, kind="unary_stream", resp_format="bytes")
        add("ReadFileRange", self._rpc_read_file_range, kind="unary_stream", resp_format="bytes")
        add("SubscribeMetadata", self._rpc_subscribe, kind="unary_stream", resp_format="json")
        add("GetFilerConfiguration", self._rpc_configuration)
        add("GetFilerConf", self._rpc_get_filer_conf)
        add("SetFilerConf", self._rpc_set_filer_conf)
        add("DeleteCollection", self._rpc_delete_collection)
        return svc

    def _rpc_delete_collection(self, req: dict, ctx) -> dict:
        """Forward a collection drop to the master (the reference's filer
        DeleteCollection does the same) — gateways only ever talk to the
        filer, so bucket deletion reclaims volumes through this hop.

        Collision guard: a collection name also serving as the filer's
        default collection, or pinned to a NON-bucket prefix by an
        fs.configure rule, holds data that is not the bucket's — dropping
        its volumes would destroy it. Refuse instead of guessing."""
        collection = req.get("collection", "")
        if collection and collection == self.collection:
            raise rpc.RpcFault(
                f"collection {collection!r} is this filer's default collection",
                grpc.StatusCode.FAILED_PRECONDITION,
            )
        for rule in self.filer_conf.rules:
            if rule.collection == collection and not rule.location_prefix.startswith(
                f"/buckets/{collection}/"
            ):
                raise rpc.RpcFault(
                    f"collection {collection!r} is mapped to "
                    f"{rule.location_prefix!r} by fs.configure",
                    grpc.StatusCode.FAILED_PRECONDITION,
                )
        return self.master.master_call("CollectionDelete", {"collection": collection})

    def _rpc_get_filer_conf(self, req: dict, ctx) -> dict:
        return {"rules": [r.to_dict() for r in self.filer_conf.rules]}

    def _rpc_set_filer_conf(self, req: dict, ctx) -> dict:
        """Upsert or delete one per-path rule (fs.configure analog); the
        whole rule set persists in the store KV so it survives restarts."""
        from seaweedfs_tpu.filer.filer_conf import CONF_KEY, PathConf

        prefix = req.get("location_prefix", "")
        if not prefix.startswith("/"):
            raise rpc.RpcFault(
                f"location_prefix must be absolute, got {prefix!r}",
                grpc.StatusCode.INVALID_ARGUMENT,
            )
        if req.get("delete"):
            found = self.filer_conf.delete(prefix)
            if not found:
                raise rpc.NotFoundFault(f"no rule for {prefix!r}")
        else:
            if req.get("replication"):
                from seaweedfs_tpu.storage.super_block import ReplicaPlacement

                ReplicaPlacement.parse(req["replication"])  # validate early
            if req.get("ttl"):
                from seaweedfs_tpu.storage.super_block import TTL

                TTL.parse(req["ttl"])
            self.filer_conf.upsert(
                PathConf(
                    location_prefix=prefix,
                    collection=req.get("collection", ""),
                    replication=req.get("replication", ""),
                    ttl=req.get("ttl", ""),
                    read_only=bool(req.get("read_only", False)),
                )
            )
        self.filer.store.kv_put(CONF_KEY, self.filer_conf.to_json())
        return {"rules": [r.to_dict() for r in self.filer_conf.rules]}

    def _rpc_lookup(self, req: dict, ctx) -> dict:
        try:
            e = self.filer.find_entry(req["path"])
        except EntryNotFound:
            raise rpc.NotFoundFault(f"{req['path']} not found")
        return {"entry": e.to_dict()}

    def _rpc_list(self, req: dict, ctx) -> dict:
        entries = self.filer.list_entries(
            req["directory"],
            start_from=req.get("start_from", ""),
            include_start=bool(req.get("inclusive_start_from", False)),
            limit=int(req.get("limit") or 1024),
            prefix=req.get("prefix", ""),
        )
        return {"entries": [e.to_dict() for e in entries]}

    def _rpc_create(self, req: dict, ctx) -> dict:
        entry = Entry.from_dict(req["entry"])
        try:
            self.filer.create_entry(entry, o_excl=bool(req.get("o_excl", False)))
        except PermissionError as e:  # fs.configure read-only prefix
            raise rpc.RpcFault(str(e), grpc.StatusCode.PERMISSION_DENIED)
        except FileExistsError:
            raise rpc.RpcFault(f"{entry.path} exists", grpc.StatusCode.ALREADY_EXISTS)
        except IsADirectoryError:
            raise rpc.RpcFault(
                f"{entry.path} is a directory", grpc.StatusCode.FAILED_PRECONDITION
            )
        return {}

    def _rpc_update(self, req: dict, ctx) -> dict:
        entry = Entry.from_dict(req["entry"])
        try:
            self.filer.update_entry(entry)
        except PermissionError as e:  # fs.configure read-only prefix
            raise rpc.RpcFault(str(e), grpc.StatusCode.PERMISSION_DENIED)
        except EntryNotFound:
            raise rpc.NotFoundFault(f"{entry.path} not found")
        return {}

    def _rpc_delete(self, req: dict, ctx) -> dict:
        try:
            self.filer.delete_entry(
                req["path"],
                recursive=bool(req.get("is_recursive", False)),
                ignore_recursive_error=bool(req.get("ignore_recursive_error", False)),
                delete_chunks=bool(req.get("is_delete_data", True)),
            )
        except PermissionError as e:  # fs.configure read-only prefix
            raise rpc.RpcFault(str(e), grpc.StatusCode.PERMISSION_DENIED)
        except EntryNotFound:
            raise rpc.NotFoundFault(f"{req['path']} not found")
        except OSError as e:
            raise rpc.RpcFault(str(e))
        return {}

    def _rpc_rename(self, req: dict, ctx) -> dict:
        try:
            self.filer.rename(req["old_path"], req["new_path"])
        except PermissionError as e:  # fs.configure read-only prefix
            raise rpc.RpcFault(str(e), grpc.StatusCode.PERMISSION_DENIED)
        except EntryNotFound:
            raise rpc.NotFoundFault(f"{req['old_path']} not found")
        except IsADirectoryError:
            raise rpc.RpcFault(
                f"{req['new_path']} is a directory", grpc.StatusCode.FAILED_PRECONDITION
            )
        return {}

    def _rpc_statistics(self, req: dict, ctx) -> dict:
        return self.master.statistics()

    def _rpc_kv_get(self, req: dict, ctx) -> dict:
        v = self.filer.store.kv_get(req["key"])
        if v is None:
            raise rpc.NotFoundFault(f"key {req['key']} not found")
        import base64

        return {"value": base64.b64encode(v).decode()}

    def _rpc_kv_put(self, req: dict, ctx) -> dict:
        import base64

        self.filer.store.kv_put(req["key"], base64.b64decode(req["value"]))
        return {}

    def _rpc_read_file(self, req: dict, ctx):
        try:
            e = self.filer.find_entry(req["path"])
        except EntryNotFound:
            raise rpc.NotFoundFault(f"{req['path']} not found")
        yield from self.chunk_io.stream_all(e.chunks)

    def _rpc_read_file_range(self, req: dict, ctx):
        """Random-access read for mount clients: only overlapping chunks
        are fetched (ChunkIO.read_range)."""
        try:
            e = self.filer.find_entry(req["path"])
        except EntryNotFound:
            raise rpc.NotFoundFault(f"{req['path']} not found")
        offset = int(req.get("offset", 0))
        size = int(req.get("size", 0))
        size = max(0, min(size, e.size - offset))
        if size > 0:
            yield self.chunk_io.read_range(e.chunks, offset, size)

    def _rpc_configuration(self, req: dict, ctx) -> dict:
        """Mount/sync clients discover the cluster through the filer, as
        the reference's GetFilerConfiguration does."""
        return {
            "masters": [self.master_address],
            "chunk_size": self.chunk_io.chunk_size,
            "collection": self.collection,
            "replication": self.replication,
        }

    def _rpc_subscribe(self, req: dict, ctx):
        """Stream MetaEvents since ts_ns; ends when the client cancels
        (gRPC termination callback sets `stop`) or after `max_idle_s`
        without events, so the handler thread never leaks."""
        since = int(req.get("since_ns", 0))
        prefix = req.get("path_prefix", "/")
        idle_limit = float(req.get("max_idle_s", 0) or 0)
        stop = threading.Event()
        ctx.add_callback(stop.set)
        for ev in self.filer.subscribe(
            since_ns=since, prefix=prefix, stop=stop, idle_timeout=idle_limit
        ):
            yield ev.to_dict()


# -- HTTP --------------------------------------------------------------------


class _ThreadingHTTPServer(httpd.ThreadingHTTPServer):
    filer_server: "FilerServer"


class _Handler(httpd.QuietHandler):
    @property
    def fs(self) -> FilerServer:
        return self.server.filer_server

    def _pq(self) -> tuple[str, dict]:
        u = urllib.parse.urlparse(self.path)
        q = {k: v[0] for k, v in urllib.parse.parse_qs(u.query).items()}
        return urllib.parse.unquote(u.path) or "/", q

    def _reply(self, code: int, body: bytes, ctype="application/octet-stream", headers=None, head=False):
        self.send_reply(code, body, ctype, headers=headers, head=head)

    def _reply_json(self, code: int, obj, head=False):
        self._reply(code, json.dumps(obj).encode(), "application/json", head=head)

    def _serve_get(self, head: bool) -> None:
        stats.FilerRequestCounter.labels("get").inc()
        path, q = self._pq()
        try:
            entry = self.fs.filer.find_entry(path)
        except EntryNotFound:
            self._reply_json(404, {"error": f"{path} not found"}, head=head)
            return
        if entry.is_directory:
            entries = self.fs.filer.list_entries(
                path,
                start_from=q.get("lastFileName", ""),
                limit=httpd.safe_int(q.get("limit"), 1024),
                prefix=q.get("prefix", ""),
            )
            accept = self.headers.get("Accept", "")
            if "text/html" in accept and "application/json" not in accept:
                # browser navigation (filer_ui analog): content-negotiated
                # HTML listing; curl/SDKs keep getting JSON
                limit = httpd.safe_int(q.get("limit"), 1024)
                self._reply_dir_html(path, entries, truncated=len(entries) >= limit, head=head)
                return
            self._reply_json(
                200,
                {
                    "Path": path,
                    "Entries": [e.to_dict() for e in entries],
                    "LastFileName": entries[-1].name if entries else "",
                },
                head=head,
            )
            return
        mime = entry.attributes.mime or "application/octet-stream"
        etag = etag_of(entry.chunks, entry.attributes.md5)
        base_headers = {
            "ETag": f'"{etag}"',
            "Last-Modified": time.strftime(
                "%a, %d %b %Y %H:%M:%S GMT", time.gmtime(entry.attributes.mtime)
            ),
            "Accept-Ranges": "bytes",
            **{k: v for k, v in entry.extended.items() if k.lower().startswith("x-")},
        }
        if head:
            base_headers["Content-Length"] = str(entry.size)
            self.send_response(200)
            self.send_header("Content-Type", mime)
            for k, v in base_headers.items():
                self.send_header(k, v)
            self.end_headers()
            return
        rng = self.headers.get("Range", "")
        if rng.startswith("bytes="):
            try:
                lo_s, hi_s = rng[len("bytes=") :].split("-", 1)
                size = entry.size
                if lo_s == "":  # suffix range: last N bytes
                    n = int(hi_s)
                    lo, hi = max(0, size - n), size - 1
                else:
                    lo = int(lo_s)
                    hi = int(hi_s) if hi_s else size - 1
                hi = min(hi, size - 1)
                if lo > hi or lo >= size:
                    self._reply_json(416, {"error": "bad range"})
                    return
                body = self.fs.chunk_io.read_range(entry.chunks, lo, hi - lo + 1)
                base_headers["Content-Range"] = f"bytes {lo}-{hi}/{size}"
                self._reply(206, body, mime, headers=base_headers)
                return
            except ValueError:
                pass
        body = self.fs.read_file(entry)
        self._reply(200, body, mime, headers=base_headers)

    def _reply_dir_html(self, path, entries, truncated: bool, head: bool) -> None:
        """HTML directory listing for browsers (filer_ui analog). Every
        name is escaped AND percent-encoded in hrefs: entry names arrive
        from arbitrary writers and render/navigate in a browser."""
        import urllib.parse as _up
        from html import escape as _esc

        crumbs, acc = ['<a href="/">/</a>'], ""
        for seg in [s for s in path.split("/") if s]:
            acc += "/" + seg
            crumbs.append(
                f'<a href="{_esc(_up.quote(acc))}/">{_esc(seg)}</a>'
            )
        rows = []
        for e in entries:
            href = _esc(_up.quote(e.path)) + ("/" if e.is_directory else "")
            name = _esc(e.name) + ("/" if e.is_directory else "")
            size = "" if e.is_directory else str(e.size)
            mtime = time.strftime(
                "%Y-%m-%d %H:%M", time.gmtime(e.attributes.mtime)
            )
            rows.append(
                f'<tr><td><a href="{href}">{name}</a></td>'
                f"<td>{size}</td><td>{mtime}</td></tr>"
            )
        more = ""
        if truncated and entries:
            nxt = _esc(
                _up.quote(path) + "?lastFileName=" + _up.quote(entries[-1].name)
            )
            more = f' &middot; <a href="{nxt}">next page &raquo;</a>'
        count = (
            f"first {len(entries)} entries" if truncated else f"{len(entries)} entries"
        )
        html = (
            "<!DOCTYPE html><html><head><title>weedtpu filer</title>"
            "<style>body{font-family:monospace}table{border-collapse:collapse}"
            "td,th{border:1px solid #999;padding:2px 8px}</style></head><body>"
            f"<h1>{' '.join(crumbs)}</h1>"
            "<table><tr><th>name</th><th>size</th><th>modified</th></tr>"
            f"{''.join(rows)}</table>"
            f"<p>{count} &middot; "
            f"store {_esc(self.fs.filer.store.name)} &middot; "
            f'<a href="/metrics">/metrics</a>{more}</p></body></html>'
        )
        self.send_reply(200, html.encode(), "text/html; charset=utf-8", head=head)

    def do_GET(self):
        self._serve_get(head=False)

    def do_HEAD(self):
        self._serve_get(head=True)

    def do_PUT(self):
        stats.FilerRequestCounter.labels("put").inc()
        path, q = self._pq()
        if "mv.from" in q:
            try:
                self.fs.filer.rename(q["mv.from"], path)
            except EntryNotFound:
                self._reply_json(404, {"error": f"{q['mv.from']} not found"})
                return
            except IsADirectoryError:
                self._reply_json(409, {"error": f"{path} is a directory"})
                return
            self._reply_json(200, {"path": path})
            return
        if path.endswith("/") or q.get("op") == "mkdir":
            self.fs.filer.mkdirs(path.rstrip("/") or "/")
            self._reply_json(201, {"path": path})
            return
        body = self.read_body()
        if body is None:
            self.reply_length_required()
            return
        extended = {
            k: v for k, v in self.headers.items() if k.lower().startswith("x-amz-")
        }
        try:
            entry = self.fs.write_file(
                path,
                io.BytesIO(body),
                mime=self.headers.get("Content-Type", ""),
                collection=q.get("collection", ""),
                replication=q.get("replication", ""),
                ttl=q.get("ttl", ""),
                extended=extended,
            )
        except IsADirectoryError:
            self._reply_json(409, {"error": f"{path} is a directory"})
            return
        except PermissionError as e:  # fs.configure read-only prefix
            self._reply_json(403, {"error": str(e)})
            return
        except Exception as e:  # noqa: BLE001 — e.g. no writable volumes:
            # answer 500 instead of killing the keep-alive connection
            self._reply_json(500, {"error": f"{type(e).__name__}: {e}"})
            return
        self._reply_json(
            201,
            {
                "name": entry.name,
                "size": entry.size,
                "etag": etag_of(entry.chunks, entry.attributes.md5),
            },
        )

    do_POST = do_PUT

    def do_DELETE(self):
        stats.FilerRequestCounter.labels("delete").inc()
        path, q = self._pq()
        try:
            self.fs.filer.delete_entry(
                path,
                recursive=q.get("recursive") == "true",
                ignore_recursive_error=q.get("ignoreRecursiveError") == "true",
            )
        except PermissionError as e:  # fs.configure read-only prefix
            self._reply_json(403, {"error": str(e)})
            return
        except EntryNotFound:
            self._reply_json(404, {"error": f"{path} not found"})
            return
        except OSError as e:
            self._reply_json(409, {"error": str(e)})
            return
        self.send_reply(204)
