"""Per-path filer configuration — mirror of weed/filer/filer_conf.go and
the fs.configure shell command [VERIFY: mount empty; SURVEY.md §2.1
"Filer" row]. A set of longest-prefix rules that pin storage policy
(collection, replication, TTL, read-only) to namespace subtrees, so e.g.
/buckets/logs/ lands in a TTL'd collection while /buckets/assets/ is
replicated 001 — without every client having to know.

Persisted as JSON in the filer KV facet under CONF_KEY (the reference
stores /etc/seaweedfs/filer.conf as a filer entry; the KV facet is this
framework's equivalent durable, store-backed slot) and applied by
FilerServer.write_file at upload time.
"""

from __future__ import annotations

import json
from dataclasses import asdict, dataclass, field
from typing import Optional

CONF_KEY = "filer.conf"


def path_prefix_match(path: str, prefix: str) -> bool:
    """Path-boundary prefix match: '/data' matches '/data' and '/data/x'
    but not '/database' — the ONE spelling of this rule shared by rule
    matching, read-only enforcement, and meta-event subscriptions."""
    if prefix == "/":
        return True
    prefix = prefix.rstrip("/")
    return path == prefix or path.startswith(prefix + "/")


@dataclass
class PathConf:
    location_prefix: str
    collection: str = ""
    replication: str = ""
    ttl: str = ""
    read_only: bool = False

    def to_dict(self) -> dict:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "PathConf":
        return cls(
            location_prefix=d["location_prefix"],
            collection=d.get("collection", ""),
            replication=d.get("replication", ""),
            ttl=d.get("ttl", ""),
            read_only=bool(d.get("read_only", False)),
        )


@dataclass
class FilerConf:
    rules: list[PathConf] = field(default_factory=list)

    def match(self, path: str) -> Optional[PathConf]:
        """Longest matching location_prefix wins (filer_conf.go semantics).

        Prefixes match on path-segment boundaries: a rule stored as
        /buckets/logs (the shell keeps the trailing slash only if the
        operator typed one) governs /buckets/logs and /buckets/logs/x but
        never the sibling /buckets/logs2/x — raw startswith would apply
        collection/TTL/read-only policy to the wrong subtree."""
        best: Optional[PathConf] = None
        for r in self.rules:
            if path_prefix_match(path, r.location_prefix or "/") and (
                best is None or len(r.location_prefix) > len(best.location_prefix)
            ):
                best = r
        return best

    def upsert(self, rule: PathConf) -> None:
        # single atomic rebind: request threads iterate self.rules without a
        # lock, and a delete-then-append window would let a mutation slip
        # past an updated read-only rule
        self.rules = [
            r for r in self.rules if r.location_prefix != rule.location_prefix
        ] + [rule]

    def delete(self, location_prefix: str) -> bool:
        before = len(self.rules)
        self.rules = [r for r in self.rules if r.location_prefix != location_prefix]
        return len(self.rules) != before

    def to_json(self) -> bytes:
        return json.dumps({"rules": [r.to_dict() for r in self.rules]}).encode()

    @classmethod
    def from_json(cls, raw: Optional[bytes]) -> "FilerConf":
        if not raw:
            return cls()
        d = json.loads(raw)
        return cls(rules=[PathConf.from_dict(r) for r in d.get("rules", [])])
