"""Entry model — mirror of weed/filer/entry.go + filechunks.go and the
Entry/FuseAttributes/FileChunk messages in weed/pb/filer.proto [VERIFY:
mount empty; SURVEY.md §2.1 "Filer" row].

An Entry is one node of the namespace: a directory, or a file whose bytes
live in `chunks` on the volume tier. `extended` carries opaque user
metadata (the S3 gateway stores x-amz-* headers there, as the reference
does in Entry.Extended).
"""

from __future__ import annotations

import posixpath
import time
from dataclasses import dataclass, field
from typing import Optional


@dataclass
class FileChunk:
    """One contiguous run of file bytes stored as a needle (fid) on the
    volume tier. `offset` is the logical position in the file."""

    fid: str
    offset: int
    size: int
    mtime_ns: int = 0
    etag: str = ""
    is_chunk_manifest: bool = False

    def to_dict(self) -> dict:
        return {
            "fid": self.fid,
            "offset": self.offset,
            "size": self.size,
            "mtime_ns": self.mtime_ns,
            "etag": self.etag,
            "is_chunk_manifest": self.is_chunk_manifest,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "FileChunk":
        return cls(
            fid=d["fid"],
            offset=int(d["offset"]),
            size=int(d["size"]),
            mtime_ns=int(d.get("mtime_ns", 0)),
            etag=d.get("etag", ""),
            is_chunk_manifest=bool(d.get("is_chunk_manifest", False)),
        )


@dataclass
class Attributes:
    """FuseAttributes analog: POSIX-ish metadata + storage options."""

    mtime: float = 0.0
    crtime: float = 0.0
    mode: int = 0o660
    uid: int = 0
    gid: int = 0
    mime: str = ""
    replication: str = ""
    collection: str = ""
    ttl_sec: int = 0
    md5: str = ""  # hex digest of the whole file (etag source)
    file_size: int = 0

    def to_dict(self) -> dict:
        return {
            "mtime": self.mtime,
            "crtime": self.crtime,
            "mode": self.mode,
            "uid": self.uid,
            "gid": self.gid,
            "mime": self.mime,
            "replication": self.replication,
            "collection": self.collection,
            "ttl_sec": self.ttl_sec,
            "md5": self.md5,
            "file_size": self.file_size,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Attributes":
        return cls(
            mtime=float(d.get("mtime", 0.0)),
            crtime=float(d.get("crtime", 0.0)),
            mode=int(d.get("mode", 0o660)),
            uid=int(d.get("uid", 0)),
            gid=int(d.get("gid", 0)),
            mime=d.get("mime", ""),
            replication=d.get("replication", ""),
            collection=d.get("collection", ""),
            ttl_sec=int(d.get("ttl_sec", 0)),
            md5=d.get("md5", ""),
            file_size=int(d.get("file_size", 0)),
        )


@dataclass
class Entry:
    """One namespace node at absolute posix `path`."""

    path: str
    is_directory: bool = False
    attributes: Attributes = field(default_factory=Attributes)
    chunks: list[FileChunk] = field(default_factory=list)
    extended: dict[str, str] = field(default_factory=dict)

    def __post_init__(self):
        self.path = normalize_path(self.path)
        if self.attributes.crtime == 0.0:
            self.attributes.crtime = self.attributes.mtime or time.time()
        if self.attributes.mtime == 0.0:
            self.attributes.mtime = self.attributes.crtime

    @property
    def dir(self) -> str:
        return posixpath.dirname(self.path) or "/"

    @property
    def name(self) -> str:
        return posixpath.basename(self.path)

    @property
    def size(self) -> int:
        if self.is_directory:
            return 0
        if self.attributes.file_size:
            return self.attributes.file_size
        return total_size(self.chunks)

    def to_dict(self) -> dict:
        return {
            "path": self.path,
            "is_directory": self.is_directory,
            "attributes": self.attributes.to_dict(),
            "chunks": [c.to_dict() for c in self.chunks],
            "extended": dict(self.extended),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Entry":
        return cls(
            path=d["path"],
            is_directory=bool(d.get("is_directory", False)),
            attributes=Attributes.from_dict(d.get("attributes", {})),
            chunks=[FileChunk.from_dict(c) for c in d.get("chunks", [])],
            extended=dict(d.get("extended", {})),
        )


def normalize_path(path: str) -> str:
    """Absolute, no trailing slash (except root), collapsed."""
    if not path.startswith("/"):
        path = "/" + path
    path = posixpath.normpath(path)
    return path


def total_size(chunks: list[FileChunk]) -> int:
    """Logical file size = max chunk extent (chunks may overlap after
    random writes; later mtime wins on read, see chunks.read_all)."""
    end = 0
    for c in chunks:
        end = max(end, c.offset + c.size)
    return end
