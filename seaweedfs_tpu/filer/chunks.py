"""Chunk I/O against the volume tier — mirror of weed/filer/filechunks.go,
filechunk_manifest.go and weed/operation upload helpers [VERIFY: mount
empty; SURVEY.md §2.1 "Filer" row].

Files larger than the chunk size are split into fixed-size chunks, each a
needle on the volume tier (assign + HTTP POST). Reads resolve the chunk
list into a visible-interval view (later mtime wins where chunks overlap
— the random-write case) and fetch the needed ranges. A chunk list past
`MANIFEST_BATCH` is folded into manifest chunks so entries stay small,
like the reference's chunk manifests.
"""

from __future__ import annotations

import hashlib
import json
import time
from dataclasses import dataclass
from typing import Callable, Iterator, Optional

from seaweedfs_tpu.cluster.client import MasterClient
from seaweedfs_tpu.filer.entry import FileChunk

DEFAULT_CHUNK_SIZE = 4 * 1024 * 1024
MANIFEST_BATCH = 1000  # fold chunk lists longer than this into manifests


class ChunkIO:
    """Upload/read/delete chunks through a MasterClient. An optional
    ChunkCache (weed/util/chunk_cache analog) front-ends reads: fids are
    immutable, so a hit never needs validation; deletes evict."""

    def __init__(self, master: MasterClient, chunk_size: int = DEFAULT_CHUNK_SIZE, cache=None):
        self.master = master
        self.chunk_size = chunk_size
        self.cache = cache

    def _read_chunk(self, fid: str) -> bytes:
        if self.cache is not None:
            hit = self.cache.get(fid)
            if hit is not None:
                return hit
        data = self.master.read(fid)
        if self.cache is not None:
            self.cache.put(fid, data)
        return data

    # -- write ----------------------------------------------------------------

    def upload_stream(
        self,
        reader,
        collection: str = "",
        replication: str = "",
        ttl: str = "",
    ) -> tuple[list[FileChunk], int, str]:
        """Split `reader` (a file-like) into chunks; returns
        (chunks, total_size, md5_hex)."""
        chunks: list[FileChunk] = []
        offset = 0
        whole = hashlib.md5()
        while True:
            data = reader.read(self.chunk_size)
            if not data:
                break
            chunks.append(
                self.upload_chunk(
                    data, offset, collection=collection, replication=replication, ttl=ttl
                )
            )
            whole.update(data)
            offset += len(data)
        return chunks, offset, whole.hexdigest()

    def upload_chunk(
        self,
        data: bytes,
        offset: int,
        collection: str = "",
        replication: str = "",
        ttl: str = "",
    ) -> FileChunk:
        a = self.master.assign(collection=collection, replication=replication, ttl=ttl)
        self.master.upload(a.fid, data, auth=a.auth)
        return FileChunk(
            fid=a.fid,
            offset=offset,
            size=len(data),
            mtime_ns=time.time_ns(),
            etag=hashlib.md5(data).hexdigest(),
        )

    # -- read -----------------------------------------------------------------

    def read_all(self, chunks: list[FileChunk]) -> bytes:
        """Materialize the whole file (visible-interval resolution)."""
        chunks = self.resolve_manifests(chunks)
        size = 0
        for c in chunks:
            size = max(size, c.offset + c.size)
        buf = bytearray(size)
        # chunks sorted by mtime: later writes overwrite earlier bytes,
        # the same winner rule as the reference's visible-interval list
        for c in sorted(chunks, key=lambda c: c.mtime_ns):
            data = self._read_chunk(c.fid)
            buf[c.offset : c.offset + c.size] = data[: c.size]
        return bytes(buf)

    def read_range(self, chunks: list[FileChunk], offset: int, size: int) -> bytes:
        """Read [offset, offset+size) fetching only overlapping chunks."""
        chunks = self.resolve_manifests(chunks)
        end = offset + size
        buf = bytearray(size)
        for c in sorted(chunks, key=lambda c: c.mtime_ns):
            lo = max(offset, c.offset)
            hi = min(end, c.offset + c.size)
            if lo >= hi:
                continue
            data = self._read_chunk(c.fid)
            buf[lo - offset : hi - offset] = data[lo - c.offset : hi - c.offset]
        return bytes(buf)

    def stream_all(self, chunks: list[FileChunk]) -> Iterator[bytes]:
        """Yield file bytes chunk by chunk (fast path: non-overlapping,
        sorted chunk lists — the common append-only upload shape)."""
        chunks = self.resolve_manifests(chunks)
        in_order = sorted(chunks, key=lambda c: c.offset)
        pos = 0
        overlapping = any(
            c.offset < (in_order[i - 1].offset + in_order[i - 1].size)
            for i, c in enumerate(in_order)
            if i > 0
        )
        if overlapping:
            yield self.read_all(chunks)
            return
        for c in in_order:
            if c.offset > pos:  # hole: sparse file, zero-fill
                yield bytes(c.offset - pos)
            yield self._read_chunk(c.fid)[: c.size]
            pos = c.offset + c.size

    # -- delete ---------------------------------------------------------------

    def delete_chunks(self, chunks: list[FileChunk]) -> None:
        for c in chunks:
            manifest = None
            if c.is_chunk_manifest:
                try:
                    manifest = self._load_manifest(c)
                except Exception:  # noqa: BLE001 — still delete the manifest needle
                    manifest = None
            if manifest:
                self.delete_chunks(manifest)
            if self.cache is not None:
                self.cache.delete(c.fid)
            try:
                self.master.delete(c.fid)
            except Exception:  # noqa: BLE001 — best-effort, orphans vacuumed later
                continue

    # -- manifests ------------------------------------------------------------

    def maybe_manifestize(
        self,
        chunks: list[FileChunk],
        collection: str = "",
        replication: str = "",
        ttl: str = "",
    ) -> list[FileChunk]:
        """Fold long chunk lists into manifest chunks (entry stays small).
        Manifest needles carry the file's storage options — same
        collection/replication/ttl fate as the data they index."""
        if len(chunks) <= MANIFEST_BATCH:
            return chunks
        out: list[FileChunk] = []
        for i in range(0, len(chunks), MANIFEST_BATCH):
            batch = chunks[i : i + MANIFEST_BATCH]
            if len(batch) == 1:
                out.append(batch[0])
                continue
            payload = json.dumps([c.to_dict() for c in batch]).encode()
            lo = min(c.offset for c in batch)
            hi = max(c.offset + c.size for c in batch)
            m = self.upload_chunk(
                payload, lo, collection=collection, replication=replication, ttl=ttl
            )
            m.size = hi - lo
            m.is_chunk_manifest = True
            out.append(m)
        return out

    def _load_manifest(self, c: FileChunk) -> list[FileChunk]:
        payload = self._read_chunk(c.fid)
        return [FileChunk.from_dict(d) for d in json.loads(payload.decode())]

    def resolve_manifests(self, chunks: list[FileChunk]) -> list[FileChunk]:
        out: list[FileChunk] = []
        for c in chunks:
            if c.is_chunk_manifest:
                out.extend(self.resolve_manifests(self._load_manifest(c)))
            else:
                out.append(c)
        return out


def etag_of(chunks: list[FileChunk], md5hex: str = "") -> str:
    """S3-style ETag: whole-file md5 when known, else multipart-style
    md5-of-chunk-md5s with a part count suffix."""
    if md5hex:
        return md5hex
    if not chunks:
        return hashlib.md5(b"").hexdigest()
    if len(chunks) == 1:
        return chunks[0].etag
    h = hashlib.md5()
    for c in sorted(chunks, key=lambda c: c.offset):
        h.update(bytes.fromhex(c.etag) if c.etag else b"")
    return f"{h.hexdigest()}-{len(chunks)}"
