"""IAM query API server — weed/iamapi/iamapi_handlers.go analog [VERIFY:
mount empty; SURVEY.md §2.1]. AWS IAM protocol subset: ListUsers,
GetUser, CreateUser, DeleteUser, CreateAccessKey, DeleteAccessKey,
PutUserPolicy (policy statements mapped onto the gateway's action list,
as the reference's iamapi does).
"""

from __future__ import annotations

import json
import secrets
import threading
import urllib.parse
import uuid
import xml.etree.ElementTree as ET
from typing import Optional

from seaweedfs_tpu.filer.client import FilerClient
from seaweedfs_tpu.s3api.auth import (
    ACTION_ADMIN,
    Iam,
    Identity,
    load_identities,
    save_identities,
)
from seaweedfs_tpu.utils import httpd
from seaweedfs_tpu.security import tls

_MUTATING = {
    "CreateUser",
    "DeleteUser",
    "CreateAccessKey",
    "DeleteAccessKey",
    "PutUserPolicy",
}


# policy Action string -> gateway action (auth_credentials.go mapping)
_POLICY_ACTIONS = {
    "s3:*": "Admin",
    "s3:GetObject": "Read",
    "s3:PutObject": "Write",
    "s3:ListBucket": "List",
    "s3:ListAllMyBuckets": "List",
    "s3:DeleteObject": "Write",
}


class IamApiServer:
    def __init__(
        self,
        filer_grpc_address: str,
        iam: Optional[Iam] = None,
        port: int = 0,
        host: str = "127.0.0.1",
        bootstrap_token: Optional[str] = None,
        extra_hosts: Optional[set[str]] = None,
    ):
        self.filer = FilerClient(filer_grpc_address)
        self.iam = iam if iam is not None else (load_identities(self.filer) or Iam())
        self.host = host
        # pre-lowercased: the auth host compare is a plain set lookup
        self.extra_hosts = {h.lower() for h in (extra_hosts or ())}
        # pre-shared secret gating the fresh-cluster bootstrap: with no
        # credentialed identity yet, only a caller presenting this token
        # may mint the first admin. Without a token configured the API is
        # CLOSED until identities arrive via config/S3 seeding — never
        # first-come-first-served (the reference has no open window at
        # all; its identities come from config).
        self.bootstrap_token = bootstrap_token
        self.lock = threading.Lock()  # identities list is shared state
        self._http = _ThreadingHTTPServer((host, port), _Handler)
        tls.maybe_wrap_https(self._http)  # data-path HTTPS when configured
        self._http.iam_server = self
        self.port = self._http.server_address[1]
        self.extra_hosts |= {f"{h}:{self.port}" for h in httpd.loopback_aliases(host)}
        self._thread = threading.Thread(target=self._http.serve_forever, daemon=True)

    @property
    def url(self) -> str:
        return f"{self.host}:{self.port}"

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._http.shutdown()
        self._http.server_close()
        self.filer.close()

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()

    def persist(self) -> None:
        save_identities(self.filer, self.iam)


class _ThreadingHTTPServer(httpd.ThreadingHTTPServer):
    iam_server: "IamApiServer"


def _resp(action: str, inner: Optional[ET.Element] = None) -> bytes:
    root = ET.Element(f"{action}Response")
    root.set("xmlns", "https://iam.amazonaws.com/doc/2010-05-08/")
    if inner is not None:
        result = ET.SubElement(root, f"{action}Result")
        result.append(inner)
    meta = ET.SubElement(root, "ResponseMetadata")
    rid = ET.SubElement(meta, "RequestId")
    rid.text = uuid.uuid4().hex
    return b'<?xml version="1.0" encoding="UTF-8"?>\n' + ET.tostring(root)


def _error(code: int, iam_code: str, msg: str = "") -> tuple[int, bytes]:
    root = ET.Element("ErrorResponse")
    err = ET.SubElement(root, "Error")
    ET.SubElement(err, "Code").text = iam_code
    ET.SubElement(err, "Message").text = msg or iam_code
    return code, b'<?xml version="1.0" encoding="UTF-8"?>\n' + ET.tostring(root)


class _Handler(httpd.QuietHandler):
    @property
    def srv(self) -> IamApiServer:
        return self.server.iam_server

    def do_POST(self):
        raw = self.read_body()
        if raw is None:
            self.reply_length_required()
            return
        # every IAM action requires SigV4 auth by an Admin identity —
        # an unauthenticated caller could otherwise mint Admin
        # credentials (PutUserPolicy s3:*) that the S3 gateway honors.
        # The gate keys on "a credentialed ADMIN exists", not "any
        # credential exists": bootstrapping in the AWS-natural order
        # (CreateUser → CreateAccessKey → PutUserPolicy) mints a key
        # with empty actions first, and gating on any-credential would
        # close the token path at that moment with no admin to sign as —
        # locking the API permanently. Fresh cluster: before deciding,
        # re-read the filer KV — an S3 gateway may have seeded
        # identities there after this server started.
        def _has_admin() -> bool:
            return any(
                i.access_key and i.can_do(ACTION_ADMIN)
                for i in self.srv.iam.identities
            )

        if not _has_admin():
            with self.srv.lock:
                fresh = load_identities(self.srv.filer)
                if fresh is not None and any(i.access_key for i in fresh.identities):
                    keys = {i.access_key for i in fresh.identities if i.access_key}
                    names = {i.name for i in fresh.identities}
                    self.srv.iam.identities = fresh.identities + [
                        i
                        for i in self.srv.iam.identities
                        if i.access_key not in keys
                        and (i.access_key or i.name not in names)
                    ]
        if _has_admin():
            u = urllib.parse.urlparse(self.path)
            headers = {k.lower(): v for k, v in self.headers.items()}
            identity, err = self.srv.iam.authenticate(
                "POST", urllib.parse.unquote(u.path) or "/", u.query, headers, raw,
                expect_service="iam",
                expect_hosts={self.srv.url.lower()} | self.srv.extra_hosts,
            )
            if identity is None:
                code, body = _error(403, err or "AccessDenied")
                self.send_reply(code, body, "text/xml")
                return
            if not identity.can_do(ACTION_ADMIN):
                code, body = _error(403, "AccessDenied", "Admin privileges required")
                self.send_reply(code, body, "text/xml")
                return
        else:
            # bootstrap: nothing to sign with yet. Gate admin minting on
            # the pre-shared token; with no token configured the API is
            # closed — first-to-reach-the-port must never become Admin.
            import hmac as _hmac

            presented = self.headers.get("x-seaweedfs-bootstrap-token", "")
            if not self.srv.bootstrap_token or not _hmac.compare_digest(
                presented, self.srv.bootstrap_token
            ):
                code, body = _error(
                    403,
                    "AccessDenied",
                    "no credentialed identities yet; bootstrap requires the "
                    "pre-shared token (-iam.bootstrapToken) or config/S3-seeded "
                    "identities",
                )
                self.send_reply(code, body, "text/xml")
                return
        form = {
            k: v[0] for k, v in urllib.parse.parse_qs(raw.decode()).items()
        }
        action = form.get("Action", "")
        handler = getattr(self, f"_do_{action}", None)
        if handler is None:
            code, body = _error(400, "InvalidAction", action)
        else:
            with self.srv.lock:
                code, body = handler(form)
                if code == 200 and action in _MUTATING:
                    self.srv.persist()
        self.send_reply(code, body, "text/xml")

    # -- actions --------------------------------------------------------------

    def _find_by_name(self, name: str) -> list[Identity]:
        return [i for i in self.srv.iam.identities if i.name == name]

    def _do_ListUsers(self, form):
        users = ET.Element("Users")
        seen = set()
        for i in self.srv.iam.identities:
            if i.name in seen:
                continue
            seen.add(i.name)
            m = ET.SubElement(users, "member")
            ET.SubElement(m, "UserName").text = i.name
        return 200, _resp("ListUsers", users)

    def _do_GetUser(self, form):
        name = form.get("UserName", "")
        if not self._find_by_name(name):
            return _error(404, "NoSuchEntity", name)
        user = ET.Element("User")
        ET.SubElement(user, "UserName").text = name
        return 200, _resp("GetUser", user)

    def _do_CreateUser(self, form):
        name = form.get("UserName", "")
        if not name:
            return _error(400, "InvalidInput")
        if self._find_by_name(name):
            return _error(409, "EntityAlreadyExists", name)
        self.srv.iam.identities.append(Identity(name, "", "", []))
        user = ET.Element("User")
        ET.SubElement(user, "UserName").text = name
        return 200, _resp("CreateUser", user)

    def _would_drop_last_admin(self, doomed) -> bool:
        """True when removing/revoking `doomed` identities leaves no
        credentialed Admin — which would silently re-open the bootstrap
        gate on a live cluster."""
        doomed_ids = {id(i) for i in doomed}
        return not any(
            i.access_key and i.can_do(ACTION_ADMIN)
            for i in self.srv.iam.identities
            if id(i) not in doomed_ids
        )

    def _do_DeleteUser(self, form):
        name = form.get("UserName", "")
        matches = self._find_by_name(name)
        if not matches:
            return _error(404, "NoSuchEntity", name)
        if self._would_drop_last_admin(matches):
            return _error(
                409, "DeleteConflict", "refusing to delete the last credentialed admin"
            )
        self.srv.iam.identities = [
            i for i in self.srv.iam.identities if i.name != name
        ]
        return 200, _resp("DeleteUser")

    def _do_CreateAccessKey(self, form):
        name = form.get("UserName", "")
        matches = self._find_by_name(name)
        access_key = "AKID" + secrets.token_hex(8)
        secret_key = secrets.token_urlsafe(24)
        if matches and not matches[0].access_key:
            # fill the empty credential slot created by CreateUser
            matches[0].access_key = access_key
            matches[0].secret_key = secret_key
        else:
            actions = matches[0].actions if matches else []
            self.srv.iam.identities.append(
                Identity(name or access_key, access_key, secret_key, list(actions))
            )
        ak = ET.Element("AccessKey")
        ET.SubElement(ak, "UserName").text = name
        ET.SubElement(ak, "AccessKeyId").text = access_key
        ET.SubElement(ak, "SecretAccessKey").text = secret_key
        ET.SubElement(ak, "Status").text = "Active"
        return 200, _resp("CreateAccessKey", ak)

    def _do_DeleteAccessKey(self, form):
        key = form.get("AccessKeyId", "")
        doomed = [i for i in self.srv.iam.identities if i.access_key == key]
        if doomed and self._would_drop_last_admin(doomed):
            return _error(
                409, "DeleteConflict", "refusing to revoke the last credentialed admin key"
            )
        # revoke the credential but keep the user (AWS semantics)
        for i in doomed:
            i.access_key = ""
            i.secret_key = ""
        return 200, _resp("DeleteAccessKey")

    def _do_PutUserPolicy(self, form):
        name = form.get("UserName", "")
        matches = self._find_by_name(name)
        if not matches:
            return _error(404, "NoSuchEntity", name)
        try:
            doc = json.loads(form.get("PolicyDocument", "{}"))
        except ValueError:
            return _error(400, "MalformedPolicyDocument")
        actions: list[str] = []
        if not isinstance(doc, dict) or not isinstance(doc.get("Statement", []), list):
            return _error(400, "MalformedPolicyDocument")
        for st in doc.get("Statement", []):
            if not isinstance(st, dict):
                return _error(400, "MalformedPolicyDocument")
            if st.get("Effect") != "Allow":
                continue
            acts = st.get("Action", [])
            if isinstance(acts, str):
                acts = [acts]
            resources = st.get("Resource", [])
            if isinstance(resources, str):
                resources = [resources]
            buckets = set()
            for r in resources:
                # arn:aws:s3:::bucket/key or *
                tail = r.rsplit(":::", 1)[-1]
                bucket = tail.split("/", 1)[0]
                if bucket and bucket != "*":
                    buckets.add(bucket)
            for a in acts:
                mapped = _POLICY_ACTIONS.get(a)
                if mapped is None:
                    continue
                if buckets:
                    actions.extend(f"{mapped}:{b}" for b in sorted(buckets))
                else:
                    actions.append(mapped)
        new_actions = sorted(set(actions))
        if ACTION_ADMIN not in new_actions and any(
            i.access_key and i.can_do(ACTION_ADMIN) for i in matches
        ):
            # demoting the sole credentialed admin would lock the IAM API
            # with no recovery path (the key still exists, so the
            # bootstrap gate stays closed) — same lockout DeleteUser guards
            if self._would_drop_last_admin(matches):
                return _error(
                    409, "DeleteConflict", "refusing to demote the last credentialed admin"
                )
        for i in matches:
            i.actions = new_actions
        return 200, _resp("PutUserPolicy")
