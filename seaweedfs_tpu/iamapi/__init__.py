"""IAM API — mirror of weed/iamapi/ [VERIFY: mount empty; SURVEY.md §2.1
"Gateways" L6 row]: an AWS-IAM-query-compatible endpoint (form-encoded
Action=CreateUser/CreateAccessKey/...) that manages the S3 gateway's
identity set. Identities persist in the filer KV store under
`s3_identities` (the reference keeps its s3 config in the filer /etc
tree), so a restarted gateway reloads them.
"""

from seaweedfs_tpu.iamapi.server import (
    IamApiServer,
    load_identities,
    save_identities,
)

__all__ = ["IamApiServer", "load_identities", "save_identities"]
