"""Master server — mirror of weed/server/master_server.go +
master_grpc_server*.go [VERIFY: mount empty; SURVEY.md §2.1 "Master" row].

Hosts the weedtpu.Master RPC service over seaweedfs_tpu.rpc: heartbeat
ingest into Topology, fid assignment (Assign -> grow volumes on demand via
the volume servers' VolumeCreate RPC), volume/EC lookup, and the topology
dump that powers shell commands. Single-master here; the reference's Raft
HA seam is the MasterServer boundary — a follower forwards to the leader.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Optional

import grpc

from seaweedfs_tpu import rpc, stats
from seaweedfs_tpu.obs import trace as trace_mod
from seaweedfs_tpu.utils import config, httpd
from seaweedfs_tpu.cluster.sequence import MemorySequencer
from seaweedfs_tpu.security.jwt import mint_file_token
from seaweedfs_tpu.cluster.topology import Topology, VolumeLayout
from seaweedfs_tpu.pb import MASTER_SERVICE, VOLUME_SERVICE, Heartbeat
from seaweedfs_tpu.storage.file_id import FileId
from seaweedfs_tpu.storage.super_block import ReplicaPlacement


class MasterServer:
    def __init__(
        self,
        port: int = 0,
        host: str = "127.0.0.1",
        volume_size_limit: Optional[int] = None,
        default_replication: str = "000",
        sequencer=None,
        reap_interval: float = 30.0,
        guard=None,
        peers: Optional[list[str]] = None,
        raft_dir: str = "",
        election_timeout: tuple[float, float] = (1.0, 2.0),
        garbage_threshold: float = 0.3,
        vacuum_interval: float = 900.0,
        http_port: Optional[int] = 0,
    ):
        self.guard = guard
        self.topology = Topology(
            **({"volume_size_limit": volume_size_limit} if volume_size_limit else {})
        )
        self.sequencer = sequencer or MemorySequencer()
        self.default_replication = default_replication
        self._rng = random.Random()
        self._grow_lock = threading.Lock()
        self._admin_locks: dict[str, tuple[int, float, str]] = {}
        # Lock-table version: (raft term, mutation seq), compared
        # lexicographically on apply. The term component dominates, so a
        # deposed leader whose local seq inflated (failed grants bump it)
        # can never out-version the new leader's table — without it, the
        # seq-gate itself would reject the fresher table and break mutual
        # exclusion across failover.
        self._lock_seq = 0
        self._lock_term = 0
        self._admin_lock_mu = threading.Lock()
        self._server = rpc.RpcServer(port=port, host=host)
        self._server.add_service(self._build_service())
        self.host = host
        self.port = self._server.port
        # HTTP facade (master_server_handlers*.go analog): the reference's
        # best-known API is `curl master:9333/dir/assign`. None disables.
        self._http = None
        if http_port is not None:
            self._http = _MasterHTTPServer((host, http_port), _MasterHttpHandler)
            self._http.master = self
            self.http_port = self._http.server_address[1]
            self._http_thread = threading.Thread(
                target=self._http.serve_forever, daemon=True
            )
        else:
            self.http_port = 0
        self._reap_interval = reap_interval
        self.garbage_threshold = garbage_threshold
        self._vacuum_interval = vacuum_interval
        self._stop = threading.Event()
        self._reaper = threading.Thread(target=self._reap_loop, daemon=True)
        self._vacuumer = threading.Thread(target=self._vacuum_loop, daemon=True)
        # fleet repair scheduler (WEEDTPU_REPAIR=on): mass-rebuild brain
        # that ranks under-replicated stripes by remaining redundancy and
        # drives batched rebuilds through the admission lane. Soft state,
        # like the topology — every master keeps a queue; only the leader
        # dispatches.
        self.repair = None
        if config.env("WEEDTPU_REPAIR") == "on":
            from seaweedfs_tpu.ec.fleet import RepairScheduler

            self.repair = RepairScheduler(self)
            self.topology.on_ec_shrink = self.repair.kick
        # raft HA (reference: master quorum; single-master when no peers)
        self.raft = None
        if peers:
            from seaweedfs_tpu.cluster.raft import RaftNode

            self.raft = RaftNode(
                me=self.address,
                peers=peers,
                server=self._server,
                state_dir=raft_dir,
                election_timeout=election_timeout,
                payload_fn=self._raft_payload,
                apply_fn=self._raft_apply,
                on_leader=self._on_become_leader,
            )

    # -- raft integration -----------------------------------------------------

    VID_TAKEOVER_MARGIN = 100  # vids the old leader could plausibly have
    # allocated beyond its last replicated watermark (each grow round-trips
    # VolumeCreate RPCs, so per heartbeat interval this is generous)

    def _raft_payload(self) -> dict:
        """Hard state the leader replicates: id watermarks + the admin
        lock table. Topology is soft state — every master rebuilds it
        from heartbeats."""
        with self.topology._lock:
            max_vid = self.topology.max_volume_id
        now = time.monotonic()
        with self._admin_lock_mu:
            locks = {
                name: {"token": tok, "ttl_s": max(0.0, exp - now), "client": client}
                for name, (tok, exp, client) in self._admin_locks.items()
                if exp > now
            }
            lock_seq, lock_term = self._lock_seq, self._lock_term
        return {
            "max_volume_id": max_vid,
            "sequence": self.sequencer.watermark,
            "admin_locks": locks,
            "lock_seq": lock_seq,
            "lock_term": lock_term,
        }

    def _raft_apply(self, payload: dict) -> None:
        with self.topology._lock:
            self.topology.max_volume_id = max(
                self.topology.max_volume_id, int(payload.get("max_volume_id", 0))
            )
        if hasattr(self.sequencer, "floor"):
            self.sequencer.floor(int(payload.get("sequence", 0)))
        # adopt the leader's lock table so a promoted follower honors
        # in-flight shell operations (mutual exclusion across failover);
        # seq-gated so a reordered heartbeat — or a stale voter payload
        # during election adoption — can never roll a fresher table back
        now = time.monotonic()
        version = (int(payload.get("lock_term", 0)), int(payload.get("lock_seq", 0)))
        with self._admin_lock_mu:
            if version >= (self._lock_term, self._lock_seq):
                self._lock_term, self._lock_seq = version
                self._admin_locks = {
                    name: (
                        int(d["token"]),
                        now + float(d["ttl_s"]),
                        d.get("client", ""),
                    )
                    for name, d in payload.get("admin_locks", {}).items()
                }

    def _on_become_leader(self) -> None:
        """A fresh leader bumps both watermarks past anything the old
        leader could have issued beyond its last replicated values."""
        if hasattr(self.sequencer, "floor"):
            self.sequencer.floor(self.sequencer.watermark + MemorySequencer.BATCH)
        with self.topology._lock:
            self.topology.max_volume_id += self.VID_TAKEOVER_MARGIN
        # No lock-table grace is needed here: lease grants are only handed
        # to clients after replicate_now() got a quorum ack, and RequestVote
        # responses carry each voter's payload — the winning candidate's
        # vote quorum intersects the ack quorum, so _raft_apply already
        # adopted any live lease before this callback runs.

    @property
    def is_leader(self) -> bool:
        return self.raft is None or self.raft.is_leader

    def _leader_address(self) -> str:
        if self.raft is None or self.raft.is_leader:
            return self.address
        return self.raft.leader or ""

    def _not_leader_response(self) -> dict:
        # one canonical key on the RPC wire; the HTTP facade re-emits it
        # as the reference's capitalized "Leader" for curl-level clients
        return {"error": "not the raft leader", "leader": self._leader_address()}

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        self._server.start()
        if self._http is not None:
            self._http_thread.start()
        if self.raft is not None:
            self.raft.start()
        self._reaper.start()
        self._vacuumer.start()
        if self.repair is not None:
            self.repair.start()

    def stop(self) -> None:
        self._stop.set()
        if self.repair is not None:
            self.repair.stop()
        if self._http is not None:
            # shutdown() blocks on an event only serve_forever() sets — a
            # never-started thread (start() raised early) must skip it
            if self._http_thread.is_alive():
                self._http.shutdown()
            self._http.server_close()
        if self.raft is not None:
            self.raft.stop()
        self._server.stop()

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()

    def _reap_loop(self) -> None:
        while not self._stop.wait(self._reap_interval):
            dead = self.topology.reap_dead_nodes()
            if dead and self.repair is not None:
                self.repair.kick("nodes reaped")

    # -- automatic vacuum (topology_vacuum.go analog) --------------------------

    def _vacuum_loop(self) -> None:
        while not self._stop.wait(self._vacuum_interval):
            if not self.is_leader:
                continue  # exactly one master drives cluster maintenance
            try:
                self.vacuum_once()
            except Exception:  # noqa: BLE001 — maintenance must never die
                pass

    def vacuum_once(self) -> list[int]:
        """One scan: compact every writable volume whose heartbeat-reported
        garbage ratio exceeds the threshold, on every holder. Returns the
        volume ids vacuumed. The reference's master does this on a timer;
        operators can still force it via `volume.vacuum` in the shell.

        Safety: the sweep defers entirely while the cluster admin lock is
        held — every mutating shell operation (ec.encode, balance, ...)
        runs under it, and compacting a volume mid-copy/encode would shift
        every needle offset under the operation's feet. Each holder is
        also re-checked with a live VolumeStatus immediately before the
        compact: the heartbeat-reported read_only flag can be a whole
        heartbeat interval stale."""
        now = time.monotonic()
        with self._admin_lock_mu:
            if any(exp > now for _, exp, _ in self._admin_locks.values()):
                return []  # operator maintenance in flight: next sweep retries
        candidates: dict[int, list[str]] = {}
        with self.topology._lock:
            for node in self.topology.nodes.values():
                for vi in node.volumes.values():
                    if vi.read_only or vi.disk_type == "remote":
                        continue  # frozen or tiered: cannot compact
                    if vi.garbage_ratio >= self.garbage_threshold:
                        candidates.setdefault(vi.id, []).append(node.grpc_address)
        done = []
        for vid, holders in sorted(candidates.items()):
            with self._admin_lock_mu:  # an operator may have locked mid-sweep
                if any(
                    exp > time.monotonic()
                    for _, exp, _ in self._admin_locks.values()
                ):
                    return done  # stop immediately; next sweep retries
            ok = True
            for addr in holders:  # every replica compacts (same live set)
                try:
                    with rpc.RpcClient(addr) as c:
                        status = c.call(
                            VOLUME_SERVICE, "VolumeStatus", {"volume_id": vid},
                            timeout=10,
                        )
                        if status.get("read_only"):
                            ok = False  # marked since the last heartbeat
                            continue
                        c.call(
                            VOLUME_SERVICE,
                            "VolumeCompact",
                            {"volume_id": vid},
                            timeout=600,
                        )
                except Exception:  # noqa: BLE001 — retried next sweep
                    ok = False
            if ok:
                done.append(vid)
        return done

    # -- RPC surface ---------------------------------------------------------

    def _build_service(self) -> rpc.Service:
        svc = rpc.Service(MASTER_SERVICE)
        svc.add("Heartbeat", self._rpc_heartbeat)
        svc.add("Assign", self._rpc_assign)
        svc.add("Lookup", self._rpc_lookup)
        svc.add("LookupEcVolume", self._rpc_lookup_ec)
        svc.add("VolumeList", self._rpc_volume_list)
        svc.add("LeaveCluster", self._rpc_leave)
        svc.add("Statistics", self._rpc_statistics)
        svc.add("LeaseAdminToken", self._rpc_lease_admin_token)
        svc.add("ReleaseAdminToken", self._rpc_release_admin_token)
        svc.add("FilerHeartbeat", self._rpc_filer_heartbeat)
        svc.add("ListClusterNodes", self._rpc_list_cluster_nodes)
        svc.add("RaftListClusterServers", self._rpc_raft_status)
        svc.add("VolumeGrow", self._rpc_volume_grow)
        svc.add("CollectionDelete", self._rpc_collection_delete)
        svc.add("RepairStatus", self._rpc_repair_status)
        return svc

    def _rpc_repair_status(self, req: dict, ctx) -> dict:
        """Fleet-repair view for `ec.status` and the chaos gates: queue
        depth, redundancy histogram, placement-violation audit, and the
        seq-ordered dispatch event log that proves 2-missing stripes
        began repair before any 1-missing stripe."""
        if self.repair is None:
            return {
                "enabled": False,
                "queue_depth": 0,
                "inflight": 0,
                "redundancy_histogram": {},
                "violations": [],
                "events": [],
                "suspects": [],
            }
        return self.repair.status()

    def _rpc_collection_delete(self, req: dict, ctx) -> dict:
        """Drop every volume and EC shard set of one collection across the
        cluster (CollectionDelete analog): per-bucket collections make an
        S3 bucket delete an O(volumes) drop instead of an O(needles) walk."""
        collection = req.get("collection", "")
        if not collection:
            # an empty name matches the DEFAULT collection: refusing it
            # here keeps a buggy caller from wiping every unlabeled volume
            raise rpc.RpcFault(
                "collection name required", code=grpc.StatusCode.INVALID_ARGUMENT
            )
        if not self.is_leader:
            raise rpc.NotLeaderFault(self._leader_address())
        with self.topology._lock:
            by_addr: dict[str, list[tuple[int, str]]] = {}
            for node in self.topology.nodes.values():
                for vid, vi in node.volumes.items():
                    if getattr(vi, "collection", "") == collection:
                        by_addr.setdefault(node.grpc_address, []).append(
                            (vid, "volume")
                        )
                for vid in node.ec_shards:
                    if self.topology.ec_collections.get(vid, "") == collection:
                        by_addr.setdefault(node.grpc_address, []).append((vid, "ec"))
        # one channel per address, short per-call timeout, addresses in
        # parallel: a dead node costs ~one timeout, not 30s x its volumes
        deleted = [0]
        dl = threading.Lock()

        def drain(addr: str, victims: list[tuple[int, str]]) -> None:
            try:
                with rpc.RpcClient(addr) as c:
                    for vid, kind in victims:
                        try:
                            if kind == "volume":
                                c.call(
                                    VOLUME_SERVICE, "VolumeDelete",
                                    {"volume_id": vid}, timeout=5,
                                )
                            else:
                                c.call(
                                    VOLUME_SERVICE, "VolumeEcShardsDelete",
                                    {"volume_id": vid, "collection": collection,
                                     "shard_ids": []},
                                    timeout=10,
                                )
                            with dl:
                                deleted[0] += 1
                        except Exception:  # noqa: BLE001 — heartbeat
                            continue  # reconciliation reaps stragglers
            except Exception:  # noqa: BLE001 — whole node unreachable
                pass

        threads = [
            threading.Thread(target=drain, args=(a, v)) for a, v in by_addr.items()
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
        return {"deleted": deleted[0]}

    def _rpc_volume_grow(self, req: dict, ctx) -> dict:
        """Pre-allocate volumes for a (collection, replication, ttl) layout
        without waiting for an Assign to trip growth (volume.grow analog)."""
        if not self.is_leader:
            raise rpc.NotLeaderFault(self._leader_address())
        collection = req.get("collection", "")
        replication = req.get("replication") or self.default_replication
        ttl = req.get("ttl", "")
        count = max(1, min(int(req.get("count", 1)), 100))
        layout = self.topology.get_layout(collection, replication, ttl)
        grown = 0
        for _ in range(count):
            grown += 1 if self._grow_volumes(
                layout, collection, replication, ttl, force=True
            ) else 0
        return {"grown": grown}

    def _rpc_raft_status(self, req: dict, ctx) -> dict:
        """Raft membership/status for cluster.raft.ps (RaftListClusterServers
        analog): which masters exist, who leads, at what term."""
        r = self.raft
        if r is None:
            return {
                "enabled": False,
                "leader": self.address,
                "state": "leader",
                "term": 0,
                "servers": [self.address],
            }
        return {
            "enabled": True,
            "leader": self._leader_address(),
            "state": r.state,
            "term": r.term,
            "servers": sorted([r.me, *r.peers]),
        }

    # -- filer registry (cluster node list, master_grpc_server_cluster.go
    # analog: filers announce themselves so shells/mounts can discover
    # them through the master) -----------------------------------------------

    FILER_TTL = 20.0

    def _rpc_filer_heartbeat(self, req: dict, ctx) -> dict:
        """Cluster-node announce for filers AND mq brokers (node_type
        distinguishes them; default 'filer' keeps old clients working)."""
        node_type = req.get("node_type") or "filer"
        with self._admin_lock_mu:  # small table; reuse the mutex
            if not hasattr(self, "_cluster_nodes"):
                self._cluster_nodes = {}
            self._cluster_nodes[(node_type, req["http_address"])] = (
                req.get("grpc_address", ""),
                time.monotonic(),
            )
        return {"leader": self._leader_address() or self.address}

    def _rpc_list_cluster_nodes(self, req: dict, ctx) -> dict:
        now = time.monotonic()
        out: dict[str, list] = {"filers": [], "brokers": []}
        with self._admin_lock_mu:
            for (node_type, url), (grpc_addr, seen) in getattr(
                self, "_cluster_nodes", {}
            ).items():
                if now - seen >= self.FILER_TTL:
                    continue
                row = {"http_address": url, "grpc_address": grpc_addr}
                if node_type == "broker":
                    out["brokers"].append(row)
                else:
                    out["filers"].append(row)
        return out

    # -- cluster exclusive lock (wdclient/exclusive_locks analog) -------------
    #
    # The shell's mutating commands (ec.encode/rebuild/balance, ...) hold a
    # cluster-wide exclusive lock leased from the master
    # [VERIFY: weed/wdclient/exclusive_locks/exclusive_locker.go; SURVEY.md §3.1].

    def _bump_lock_version(self) -> None:
        """Advance the lock-table version (caller holds _admin_lock_mu):
        stamp the current raft term so this table out-versions anything a
        deposed leader produced in an earlier term."""
        self._lock_term = getattr(self.raft, "term", 0) if self.raft else 0
        self._lock_seq += 1

    ADMIN_LOCK_TTL = 30.0

    def _rpc_lease_admin_token(self, req: dict, ctx) -> dict:
        if not self.is_leader:
            raise rpc.NotLeaderFault(self._leader_address())
        name = req.get("lock_name") or "admin"
        prev = int(req.get("previous_token", 0))
        now = time.monotonic()
        with self._admin_lock_mu:
            holder = self._admin_locks.get(name)
            if holder is not None and holder[1] > now and holder[0] != prev:
                raise rpc.RpcFault(
                    f"lock {name} held by {holder[2]}",
                    code=grpc.StatusCode.FAILED_PRECONDITION,
                )
            token = prev if (holder is not None and holder[0] == prev) else (
                self._rng.getrandbits(63) or 1
            )
            self._admin_locks[name] = (
                token,
                now + self.ADMIN_LOCK_TTL,
                req.get("client_name", ""),
            )
            self._bump_lock_version()
        # The lease is only durable once a quorum has seen it: replicate
        # synchronously BEFORE handing out the token, so a leader crash can
        # never lose a lock a client believes it holds (the new leader
        # adopts the table from its vote quorum, which intersects the ack
        # quorum). Replication happens outside the mutex — payload_fn locks.
        if self.raft is not None and not self.raft.replicate_now():
            with self._admin_lock_mu:
                cur = self._admin_locks.get(name)
                if cur is not None and cur[0] == token:
                    if holder is not None:
                        self._admin_locks[name] = holder  # restore prior lease
                    else:
                        del self._admin_locks[name]
                    self._bump_lock_version()
            raise rpc.RpcFault(
                f"lock {name} lease not acknowledged by a master quorum",
                code=grpc.StatusCode.UNAVAILABLE,
            )
        return {"token": token, "lock_ts_ns": int(now * 1e9)}

    def _rpc_release_admin_token(self, req: dict, ctx) -> dict:
        if not self.is_leader:
            # must land on the leader: a follower-local delete is lost and
            # the replicated lock table keeps the cluster locked till TTL
            raise rpc.NotLeaderFault(self._leader_address())
        name = req.get("lock_name") or "admin"
        prev = int(req.get("previous_token", 0))
        with self._admin_lock_mu:
            holder = self._admin_locks.get(name)
            if holder is not None and holder[0] == prev:
                del self._admin_locks[name]
                self._bump_lock_version()
        # release is best-effort: the next heartbeat replicates the removal,
        # and the TTL bounds how long a follower could consider it held
        return {}

    def _rpc_heartbeat(self, req: dict, ctx) -> dict:
        # every master ingests heartbeats (topology is soft state — a
        # follower promoted by raft already has a live view); the reply
        # names the current leader so volume servers can prefer it
        stats.MasterReceivedHeartbeatCounter.inc()
        hb = Heartbeat.from_dict(req)
        self.topology.process_heartbeat(hb)
        if self.repair is not None and hb.unreachable_peers:
            self.repair.note_reports(hb.url, hb.unreachable_peers)
        return {
            "volume_size_limit": self.topology.volume_size_limit,
            "leader": self._leader_address() or self.address,
        }

    def _rpc_leave(self, req: dict, ctx) -> dict:
        self.topology.unregister_node(req["url"])
        return {}

    def _rpc_assign(self, req: dict, ctx) -> dict:
        if not self.is_leader:
            # followers redirect: only the leader allocates ids/volumes
            return {**self._not_leader_response(), "count": 0}
        # clamped at the RPC layer: a negative count would REWIND the id
        # sequencer (duplicate fids overwriting live needles), and the
        # count reaches here unauthenticated via the HTTP facade
        count = max(1, min(int(req.get("count", 1)), 10000))
        collection = req.get("collection", "")
        replication = req.get("replication") or self.default_replication
        ttl = req.get("ttl", "")
        layout = self.topology.get_layout(collection, replication, ttl)
        picked = self.topology.pick_writable(layout, self._rng)
        if picked is None:
            self._grow_volumes(layout, collection, replication, ttl)
            picked = self.topology.pick_writable(layout, self._rng)
        if picked is None:
            return {"error": "no writable volumes and growth failed", "count": 0}
        vid, nodes = picked
        key = self.sequencer.next_ids(count)
        cookie = self._rng.getrandbits(32)
        node = nodes[self._rng.randrange(len(nodes))]
        stats.MasterAssignCounter.inc()
        fid = str(FileId(vid, key, cookie))
        resp = {
            "fid": fid,
            "url": node.url,
            "public_url": node.public_url,
            "grpc_port": node.grpc_port,
            "count": count,
        }
        if self.guard is not None and self.guard.signing_key:
            # token the client must present to the volume server (jwt.go analog)
            resp["auth"] = mint_file_token(
                self.guard.signing_key, fid, self.guard.expires_seconds
            )
        return resp

    def _rpc_lookup(self, req: dict, ctx) -> dict:
        out = []
        for raw in req.get("volume_or_file_ids", []):
            vid_s = str(raw).split(",", 1)[0]
            try:
                vid = int(vid_s)
            except ValueError:
                out.append({"volume_id": vid_s, "error": "bad volume id", "locations": []})
                continue
            nodes = self.topology.lookup(vid, req.get("collection", ""))
            if not nodes:
                # EC volume: any shard holder can serve the (degraded) read
                seen = set()
                for holders in self.topology.lookup_ec_shards(vid).values():
                    for n in holders:
                        if n.url not in seen:
                            seen.add(n.url)
                            nodes.append(n)
            entry = {
                "volume_id": vid_s,
                "locations": [
                    {"url": n.url, "public_url": n.public_url, "grpc_port": n.grpc_port}
                    for n in nodes
                ],
            }
            if not nodes:
                entry["error"] = "volume not found"
            out.append(entry)
        return {"volume_id_locations": out}

    def _rpc_lookup_ec(self, req: dict, ctx) -> dict:
        vid = int(req["volume_id"])
        shard_map = self.topology.lookup_ec_shards(vid)
        if not shard_map:
            raise rpc.NotFoundFault(f"ec volume {vid} not found")
        # each holder carries its failure-domain labels: readers sort
        # their survivor/hedge ladders same-rack-first on ties, so a
        # degraded read prefers the cheap fetch without a master
        # round-trip at decision time
        return {
            "volume_id": vid,
            "shard_id_locations": [
                {
                    "shard_id": sid,
                    "locations": [
                        {
                            "url": n.url,
                            "public_url": n.public_url,
                            "grpc_port": n.grpc_port,
                            "data_center": n.data_center,
                            "rack": n.rack,
                        }
                        for n in nodes
                    ],
                }
                for sid, nodes in sorted(shard_map.items())
            ],
        }

    def _rpc_volume_list(self, req: dict, ctx) -> dict:
        return self.topology.to_dict()

    def _rpc_statistics(self, req: dict, ctx) -> dict:
        t = self.topology
        with t._lock:
            total = sum(n.max_volume_count for n in t.nodes.values())
            used = sum(len(n.volumes) for n in t.nodes.values())
            return {
                "node_count": len(t.nodes),
                "volume_count": used,
                "max_volume_count": total,
                "ec_volume_count": len(t.ec_locations),
            }

    # -- growth (volume_growth.go analog) ------------------------------------

    def _grow_volumes(
        self,
        layout: VolumeLayout,
        collection: str,
        replication: str,
        ttl: str,
        force: bool = False,
    ) -> int:
        """Create one new volume (all replicas) via VolumeCreate RPCs.
        `force` skips the already-writable short-circuit (volume.grow's
        explicit pre-allocation)."""
        with self._grow_lock:
            if not force and self.topology.pick_writable(layout, self._rng) is not None:
                return 0  # raced: someone grew while we waited
            rp = ReplicaPlacement.parse(replication or "000")
            targets = self.topology.place_replicas(rp)
            if not targets:
                return 0
            vid = self.topology.next_volume_id()
            if self.raft is not None:
                # replicate the new watermark eagerly so a crash right
                # after the creates can't lead the next leader to reissue
                # this vid (belt; VID_TAKEOVER_MARGIN is the suspenders)
                self.raft._broadcast_heartbeat()
            succeeded = []
            for node in targets:
                try:
                    with rpc.RpcClient(node.grpc_address) as c:
                        c.call(
                            VOLUME_SERVICE,
                            "VolumeCreate",
                            {
                                "volume_id": vid,
                                "collection": collection,
                                "replication": replication or "000",
                                "ttl": ttl,
                            },
                        )
                    succeeded.append(node)
                except Exception:  # noqa: BLE001 — skip unreachable node
                    continue
            # registration happens via the next heartbeats; to serve the
            # pending Assign immediately, register the nodes whose create
            # actually succeeded
            if succeeded:
                from seaweedfs_tpu.pb import VolumeInformation

                with self.topology._lock:
                    for node in succeeded:
                        vi = VolumeInformation(
                            id=vid,
                            collection=collection,
                            replica_placement=replication or "000",
                            ttl=ttl,
                        )
                        node.volumes[vid] = vi
                        layout.register(vi, node)
            return len(succeeded)


# -- HTTP facade (master_server_handlers*.go analog) --------------------------
#
# The reference master's HTTP API is its most-used surface:
#   GET/POST /dir/assign?count=&collection=&replication=&ttl=
#   GET      /dir/lookup?volumeId=<vid or fid>
#   GET      /dir/status           topology dump
#   GET      /cluster/status       raft leadership
#   GET      /cluster/healthz      liveness probe
#   GET      /vol/grow?count=&collection=&replication=&ttl=
#   GET      /col/delete?collection=
#   GET      /metrics              Prometheus text
# Field names follow the reference's JSON (fid/url/publicUrl/count).


class _MasterHTTPServer(httpd.ThreadingHTTPServer):
    master: "MasterServer"


class _MasterHttpHandler(httpd.QuietHandler):
    protocol_version = "HTTP/1.1"

    @property
    def m(self) -> "MasterServer":
        return self.server.master

    def _json(self, code: int, obj: dict) -> None:
        import json as _json

        tid = trace_mod.current_trace_id()
        self.send_reply(
            code, _json.dumps(obj).encode(), "application/json",
            headers={trace_mod.HTTP_HEADER: tid} if tid else None,
        )

    def _route(self):
        import urllib.parse as _up

        path = _up.urlparse(self.path).path
        if path == "/debug/traces":
            self._json(200, trace_mod.debug_payload(self.path))
            return
        if path in ("/metrics", "/cluster/healthz"):
            self._route_inner()  # scrape/probe paths must not churn the ring
            return
        with trace_mod.start(
            "master.http",
            klass="master",
            trace_id=self.headers.get(trace_mod.HTTP_HEADER),
        ):
            trace_mod.annotate(path=path)
            self._route_inner()

    def _route_inner(self):
        import urllib.parse as _up

        u = _up.urlparse(self.path)
        q = {k: v[0] for k, v in _up.parse_qs(u.query).items()}
        path = u.path
        m = self.m
        try:
            if path == "/dir/assign":
                resp = m._rpc_assign(
                    {
                        "count": httpd.safe_int(q.get("count"), 1),
                        "collection": q.get("collection", ""),
                        "replication": q.get("replication", ""),
                        "ttl": q.get("ttl", ""),
                    },
                    None,
                )
                out = {
                    "fid": resp.get("fid", ""),
                    "url": resp.get("url", ""),
                    "publicUrl": resp.get("public_url", ""),
                    "count": resp.get("count", 0),
                }
                if resp.get("error"):
                    out["error"] = resp["error"]
                    # follower answering: name the leader so curl-level
                    # clients can fail over (reference HTTP error shape)
                    if resp.get("Leader") or resp.get("leader"):
                        out["Leader"] = resp.get("Leader") or resp["leader"]
                if resp.get("auth"):
                    out["auth"] = resp["auth"]
                self._json(200, out)
            elif path == "/dir/lookup":
                vid = q.get("volumeId", "")
                resp = m._rpc_lookup({"volume_or_file_ids": [vid]}, None)
                entry = resp["volume_id_locations"][0]
                out = {
                    "volumeId": entry["volume_id"],
                    "locations": [
                        {"url": l["url"], "publicUrl": l["public_url"]}
                        for l in entry["locations"]
                    ],
                }
                if entry.get("error"):
                    out["error"] = entry["error"]
                self._json(200 if not entry.get("error") else 404, out)
            elif path == "/dir/status":
                self._json(200, {"Topology": m.topology.to_dict()})
            elif path == "/cluster/status":
                st = m._rpc_raft_status({}, None)
                self._json(
                    200,
                    {
                        "IsLeader": m.is_leader,
                        "Leader": st.get("leader"),
                        "Peers": st.get("servers", []),
                    },
                )
            elif path == "/cluster/healthz":
                self.send_reply(200, b"ok", "text/plain")
            elif path == "/vol/grow":
                resp = m._rpc_volume_grow(
                    {
                        "count": httpd.safe_int(q.get("count"), 1),
                        "collection": q.get("collection", ""),
                        "replication": q.get("replication", ""),
                        "ttl": q.get("ttl", ""),
                    },
                    None,
                )
                self._json(200, resp)
            elif path == "/col/delete":
                resp = m._rpc_collection_delete(
                    {"collection": q.get("collection", "")}, None
                )
                self._json(200, resp)
            elif path == "/metrics":
                self.send_reply(
                    200, stats.REGISTRY.expose().encode(),
                    "text/plain; version=0.0.4",
                )
            elif path in ("/", "/ui", "/ui/index.html"):
                # operator status page (master_server_handlers_ui.go analog)
                # escaped throughout: dc/rack/url names arrive from
                # unauthenticated heartbeats and render in a browser
                from html import escape as _esc

                topo = m.topology.to_dict()
                node_rows = []
                for dc, racks in sorted(topo.get("data_centers", {}).items()):
                    for rack, nodes in sorted(racks.items()):
                        for n in nodes:
                            node_rows.append(
                                f"<tr><td>{_esc(str(dc))}</td>"
                                f"<td>{_esc(str(rack))}</td>"
                                f"<td>{_esc(str(n['url']))}</td>"
                                f"<td>:{int(n['grpc_port'])}</td>"
                                f"<td>{len(n.get('volumes', []))}"
                                f"/{int(n.get('max_volume_count', 0))}</td>"
                                f"<td>{len(n.get('ec_shards', []))}</td></tr>"
                            )
                st = m._rpc_raft_status({}, None)
                html = (
                    "<!DOCTYPE html><html><head><title>weedtpu master</title>"
                    "<style>body{font-family:monospace}table{border-collapse:"
                    "collapse}td,th{border:1px solid #999;padding:2px 8px}"
                    "</style></head><body>"
                    f"<h1>Master {_esc(m.address)}</h1>"
                    f"<p>leader: {_esc(str(st.get('leader')))} &middot; "
                    f"term {int(st.get('term', 0))}"
                    f" &middot; volume size limit "
                    f"{int(topo.get('volume_size_limit', 0))}</p>"
                    "<h2>Topology</h2><table><tr><th>dc</th><th>rack</th>"
                    "<th>node</th><th>grpc</th><th>volumes</th><th>ec</th></tr>"
                    f"{''.join(node_rows)}</table>"
                    '<p><a href="/dir/status">/dir/status</a> &middot; '
                    '<a href="/cluster/status">/cluster/status</a> &middot; '
                    '<a href="/metrics">/metrics</a></p></body></html>'
                )
                self.send_reply(200, html.encode(), "text/html; charset=utf-8")
            else:
                self._json(404, {"error": f"unknown path {path}"})
        except rpc.NotLeaderFault as e:
            # the reference's HTTP masters answer follower hits with the
            # leader in the JSON shape so curl-level clients can fail over
            # ([ref: weed/server/master_server_handlers_admin.go — mount
            # empty]); a bare 412 left HA clients with an opaque failure
            self._json(200, {"error": e.detail, "Leader": e.leader})
        except rpc.RpcFault as e:
            self._json(412, {"error": str(e)})
        except Exception as e:  # noqa: BLE001 — facade must not kill keep-alive
            self._json(500, {"error": f"{type(e).__name__}: {e}"})

    def do_GET(self):
        self._route()

    def do_POST(self):
        # drain framing; assign params ride the query string. A chunked
        # body can't be drained (read_body -> None): unread bytes would
        # desync keep-alive, so answer 411 per the helper's contract.
        if self.read_body() is None:
            self.reply_length_required()
            return
        self._route()
