"""Cluster topology — mirror of weed/topology (topology.go, topology_ec.go,
data_node.go, rack.go, data_center.go, volume_layout.go, volume_growth.go)
[VERIFY: mount empty; SURVEY.md §2.1 "Topology" row, §3.5 membership].

DC -> rack -> node tree fed by volume-server heartbeats; per-(collection,
replication, ttl) VolumeLayout tracking writable volumes and locations; the
EcShardLocations registry (vid -> shard id -> nodes); replica-placement-aware
volume growth. Pure in-process data structure — the master server wraps it
with RPC; tests drive it with fake heartbeats (SURVEY.md §4)."""

from __future__ import annotations

import threading
import time
from typing import Optional

from seaweedfs_tpu.ec.shard_bits import EcVolumeInfo, ShardBits
from seaweedfs_tpu.pb import Heartbeat, VolumeInformation
from seaweedfs_tpu.storage.super_block import ReplicaPlacement

VOLUME_SIZE_LIMIT = 30 * 1024 * 1024 * 1024  # 30 GB, the reference default
DEAD_NODE_SECONDS = 5 * 60


class DataNode:
    def __init__(self, hb: Heartbeat):
        self.ip = hb.ip
        self.port = hb.port
        self.grpc_port = hb.grpc_port
        self.public_url = hb.public_url or hb.url
        self.data_center = hb.data_center
        self.rack = hb.rack
        self.max_volume_count = hb.max_volume_count
        self.volumes: dict[int, VolumeInformation] = {}
        self.ec_shards: dict[int, ShardBits] = {}
        self.last_seen = time.monotonic()

    @property
    def url(self) -> str:
        return f"{self.ip}:{self.port}"

    @property
    def grpc_address(self) -> str:
        return f"{self.ip}:{self.grpc_port}"

    def is_alive(self, now: Optional[float] = None) -> bool:
        return ((now or time.monotonic()) - self.last_seen) < DEAD_NODE_SECONDS

    def free_slots(self) -> int:
        # an EC volume's shard set costs roughly shards/total of a slot;
        # count any presence as one slot for simplicity (reference counts
        # ec shards separately against max)
        return self.max_volume_count - len(self.volumes) - len(self.ec_shards)

    def to_dict(self) -> dict:
        return {
            "url": self.url,
            "public_url": self.public_url,
            "grpc_port": self.grpc_port,
            "data_center": self.data_center,
            "rack": self.rack,
            "max_volume_count": self.max_volume_count,
            "volumes": [v.to_dict() for v in self.volumes.values()],
            "ec_shards": [
                EcVolumeInfo(vid, shard_bits=bits).to_dict()
                for vid, bits in self.ec_shards.items()
            ],
        }


class VolumeLayout:
    """Writable/readonly volume tracking for one (collection, rp, ttl)."""

    def __init__(self, replica_placement: ReplicaPlacement, ttl: str):
        self.rp = replica_placement
        self.ttl = ttl
        self.locations: dict[int, list[DataNode]] = {}
        self.writable: set[int] = set()
        self.readonly: set[int] = set()

    def register(self, vi: VolumeInformation, node: DataNode) -> None:
        nodes = self.locations.setdefault(vi.id, [])
        if node not in nodes:
            nodes.append(node)
        if vi.read_only or vi.size >= VOLUME_SIZE_LIMIT:
            self.readonly.add(vi.id)
            self.writable.discard(vi.id)
        elif len(nodes) >= self.rp.copy_count:
            self.readonly.discard(vi.id)
            self.writable.add(vi.id)

    def unregister(self, vid: int, node: DataNode) -> None:
        nodes = self.locations.get(vid)
        if not nodes:
            return
        if node in nodes:
            nodes.remove(node)
        if not nodes:
            del self.locations[vid]
            self.writable.discard(vid)
            self.readonly.discard(vid)
        elif len(nodes) < self.rp.copy_count:
            self.writable.discard(vid)

    def pick_writable(self, rng) -> Optional[int]:
        if not self.writable:
            return None
        return rng.choice(sorted(self.writable))


def _layout_key(collection: str, replication: str, ttl: str) -> tuple:
    return (collection, replication, ttl)


class Topology:
    def __init__(self, volume_size_limit: int = VOLUME_SIZE_LIMIT):
        self._lock = threading.RLock()
        self.volume_size_limit = volume_size_limit
        self.nodes: dict[str, DataNode] = {}  # url -> node
        self.layouts: dict[tuple, VolumeLayout] = {}
        # EC registry: vid -> {shard_id -> set of node urls}
        self.ec_locations: dict[int, dict[int, set[str]]] = {}
        self.ec_collections: dict[int, str] = {}
        # per-volume code geometry + shard size, from heartbeats: the
        # repair scheduler ranks stripes by bytes at risk and computes
        # missing counts against the VOLUME's (k, k+m), not the legacy 14
        self.ec_geometry: dict[int, dict] = {}
        self.max_volume_id = 0
        # optional observer (the master's repair scheduler): called OUTSIDE
        # the topology lock whenever a heartbeat/unregister SHRANK some
        # node's EC shard coverage — the death/quarantine signal that makes
        # mass repair react in heartbeat time instead of scan time
        self.on_ec_shrink = None

    # -- heartbeat ingest ----------------------------------------------------

    def process_heartbeat(self, hb: Heartbeat) -> None:
        shrank = False
        with self._lock:
            node = self.nodes.get(hb.url)
            if node is None:
                node = DataNode(hb)
                self.nodes[hb.url] = node
            node.last_seen = time.monotonic()
            node.max_volume_count = hb.max_volume_count
            node.grpc_port = hb.grpc_port
            node.public_url = hb.public_url or hb.url
            node.data_center = hb.data_center
            node.rack = hb.rack

            new_volumes = {}
            for vd in hb.volumes:
                vi = VolumeInformation.from_dict(vd)
                new_volumes[vi.id] = vi
                self.max_volume_id = max(self.max_volume_id, vi.id)
            # unregister volumes that disappeared
            for vid in set(node.volumes) - set(new_volumes):
                self._layout_for_volume(node.volumes[vid]).unregister(vid, node)
            node.volumes = new_volumes
            for vi in new_volumes.values():
                self._layout_for_volume(vi).register(vi, node)

            new_shards: dict[int, ShardBits] = {}
            for ed in hb.ec_shards:
                info = EcVolumeInfo.from_dict(ed)
                new_shards[info.volume_id] = info.shard_bits
                self.max_volume_id = max(self.max_volume_id, info.volume_id)
                if getattr(info, "collection", ""):
                    self.ec_collections[info.volume_id] = info.collection
                if info.total_shards or info.shard_size:
                    self.ec_geometry[info.volume_id] = {
                        "data_shards": info.data_shards,
                        "total_shards": info.total_shards,
                        "shard_size": info.shard_size,
                    }
            for vid, bits in node.ec_shards.items():
                if bits.minus(new_shards.get(vid, ShardBits(0))):
                    shrank = True  # some shard this node held is gone
            self._sync_ec_shards(node, new_shards)
            node.ec_shards = new_shards
        if shrank and self.on_ec_shrink is not None:
            try:
                self.on_ec_shrink()
            except Exception:  # noqa: BLE001 — observers must not break ingest
                pass

    def _sync_ec_shards(self, node: DataNode, new: dict[int, ShardBits]) -> None:
        old = node.ec_shards
        for vid in set(old) | set(new):
            old_bits = old.get(vid, ShardBits(0))
            new_bits = new.get(vid, ShardBits(0))
            for sid in old_bits.minus(new_bits).shard_ids():
                holders = self.ec_locations.get(vid, {}).get(sid)
                if holders:
                    holders.discard(node.url)
            for sid in new_bits.shard_ids():
                self.ec_locations.setdefault(vid, {}).setdefault(sid, set()).add(node.url)
        # drop empty registries
        for vid in list(self.ec_locations):
            m = self.ec_locations[vid]
            for sid in list(m):
                if not m[sid]:
                    del m[sid]
            if not m:
                del self.ec_locations[vid]
                self.ec_collections.pop(vid, None)
                self.ec_geometry.pop(vid, None)

    def unregister_node(self, url: str) -> None:
        with self._lock:
            node = self.nodes.pop(url, None)
            if node is None:
                return
            for vi in node.volumes.values():
                self._layout_for_volume(vi).unregister(vi.id, node)
            held_ec = bool(node.ec_shards)
            self._sync_ec_shards(node, {})
        if held_ec and self.on_ec_shrink is not None:
            try:
                self.on_ec_shrink()
            except Exception:  # noqa: BLE001 — observers must not break ingest
                pass

    def reap_dead_nodes(self) -> list[str]:
        with self._lock:
            now = time.monotonic()
            dead = [u for u, n in self.nodes.items() if not n.is_alive(now)]
        for u in dead:
            self.unregister_node(u)
        return dead

    # -- layouts / lookup ----------------------------------------------------

    def _layout_for_volume(self, vi: VolumeInformation) -> VolumeLayout:
        return self.get_layout(vi.collection, vi.replica_placement, vi.ttl)

    def get_layout(self, collection: str, replication: str, ttl: str) -> VolumeLayout:
        with self._lock:
            key = _layout_key(collection, replication or "000", ttl)
            layout = self.layouts.get(key)
            if layout is None:
                layout = VolumeLayout(ReplicaPlacement.parse(replication or "000"), ttl)
                self.layouts[key] = layout
            return layout

    def pick_writable(self, layout: VolumeLayout, rng) -> Optional[tuple[int, list[DataNode]]]:
        """(vid, locations) for a writable volume of `layout`, chosen under
        the topology lock so heartbeat ingest can't race the read."""
        with self._lock:
            vid = layout.pick_writable(rng)
            if vid is None:
                return None
            return vid, list(layout.locations.get(vid, []))

    def lookup(self, vid: int, collection: str = "") -> list[DataNode]:
        """All nodes holding `vid` as a normal volume (any layout)."""
        with self._lock:
            out: list[DataNode] = []
            for layout in self.layouts.values():
                for node in layout.locations.get(vid, []):
                    if node not in out:
                        out.append(node)
            return out

    def lookup_ec_shards(self, vid: int) -> dict[int, list[DataNode]]:
        with self._lock:
            m = self.ec_locations.get(vid, {})
            return {
                sid: [self.nodes[u] for u in urls if u in self.nodes]
                for sid, urls in m.items()
            }

    def next_volume_id(self) -> int:
        with self._lock:
            self.max_volume_id += 1
            return self.max_volume_id

    # -- placement (volume_growth.go analog) ---------------------------------

    def place_replicas(self, rp: ReplicaPlacement) -> Optional[list[DataNode]]:
        """Pick copy_count nodes honoring the xyz placement digits:
        same_rack extra copies on the primary's rack, diff_rack copies on
        other racks of the primary's DC, diff_dc copies in other DCs."""
        with self._lock:
            alive = [n for n in self.nodes.values() if n.is_alive() and n.free_slots() > 0]
            if not alive:
                return None
            alive.sort(key=lambda n: -n.free_slots())
            primary = alive[0]
            chosen = [primary]

            def pick(pred, count):
                got = []
                for n in alive:
                    if len(got) >= count:
                        break
                    if n not in chosen and pred(n):
                        got.append(n)
                return got if len(got) >= count else None

            same_rack = pick(
                lambda n: n.data_center == primary.data_center and n.rack == primary.rack,
                rp.same_rack,
            )
            if same_rack is None:
                return None
            chosen += same_rack
            diff_rack = pick(
                lambda n: n.data_center == primary.data_center and n.rack != primary.rack,
                rp.diff_rack,
            )
            if diff_rack is None:
                return None
            chosen += diff_rack
            diff_dc = pick(lambda n: n.data_center != primary.data_center, rp.diff_dc)
            if diff_dc is None:
                return None
            chosen += diff_dc
            return chosen

    # -- introspection -------------------------------------------------------

    def to_dict(self) -> dict:
        with self._lock:
            dcs: dict[str, dict[str, list[dict]]] = {}
            for node in self.nodes.values():
                dcs.setdefault(node.data_center, {}).setdefault(node.rack, []).append(
                    node.to_dict()
                )
            return {
                "max_volume_id": self.max_volume_id,
                "volume_size_limit": self.volume_size_limit,
                "data_centers": dcs,
                "ec_volumes": {
                    str(vid): {str(sid): sorted(urls) for sid, urls in m.items()}
                    for vid, m in self.ec_locations.items()
                },
                "ec_collections": {
                    str(vid): coll for vid, coll in self.ec_collections.items()
                },
            }
