"""Client library — mirror of weed/wdclient (masterclient.go, vid_map.go) +
weed/operation (assign_file_id.go, upload_content.go, lookup.go,
delete_content.go, submit.go) [VERIFY: mount empty; SURVEY.md §2.1].

MasterClient caches vid -> locations (the reference keeps it fresh via the
KeepConnected stream; here a TTL cache refreshed by Lookup on miss/expiry).
Operations: assign, upload (HTTP POST to the volume server), read, delete,
and submit (assign+upload in one call).
"""

from __future__ import annotations

import http.client
import socket
import time
import threading
import urllib.error
import urllib.request
from dataclasses import dataclass
from typing import Optional

from seaweedfs_tpu import rpc
from seaweedfs_tpu.obs import trace as trace_mod
from seaweedfs_tpu.pb import MASTER_SERVICE, AssignResponse, Location
from seaweedfs_tpu.security import tls
from seaweedfs_tpu.security.jwt import mint_file_token

_VID_CACHE_TTL = 30.0

# Errors that mean "this replica is unusable, try the next one". A wedged
# server surfaces a bare TimeoutError/ConnectionError from the socket layer
# (NOT urllib.error.URLError) — catching only URLError would abort failover.
_FAILOVER_ERRORS = (
    urllib.error.URLError,
    TimeoutError,
    ConnectionError,
    http.client.HTTPException,
)


class ClusterError(Exception):
    pass


def _trace_headers() -> dict:
    """X-Weedtpu-Trace header when a trace is active in this thread —
    the HTTP half of cross-process propagation (the RPC half rides gRPC
    metadata inside RpcClient)."""
    tid = trace_mod.current_trace_id()
    return {trace_mod.HTTP_HEADER: tid} if tid else {}


@dataclass
class SubmitResult:
    fid: str
    url: str
    size: int


class MasterClient:
    def __init__(
        self,
        master_address: str,
        signing_key: Optional[bytes] = None,
        read_signing_key: Optional[bytes] = None,
        http_timeout: float = 30.0,
    ):
        """Trusted clients share the cluster's security.toml keys and mint
        their own per-fid JWTs for delete/read (the reference's clients do
        the same; Assign only covers the freshly assigned fid).

        `master_address` may be a comma-separated HA quorum list; calls
        fail over between masters and follow raft-leader redirects."""
        self.addresses = [a.strip() for a in master_address.split(",") if a.strip()]
        self.master_address = self.addresses[0]
        self.signing_key = signing_key
        self.read_signing_key = read_signing_key
        self.http_timeout = http_timeout
        self._clients: dict[str, rpc.RpcClient] = {}
        self._current = self.addresses[0]
        self._lock = threading.Lock()
        self._vid_cache: dict[int, tuple[float, list[Location]]] = {}
        # per-thread keep-alive connections to volume servers: read_ex
        # reuses them instead of a fresh TCP connect per request (the
        # volume server speaks HTTP/1.1, and thread-per-connection on
        # its side makes connection churn the dominant per-read cost at
        # kilo-rps). Plain-HTTP only; TLS clusters take the urllib path.
        self._tl = threading.local()
        # location suspicion (client half of the planner's holder
        # suspicion ladder): a replica that just failed over is tried
        # LAST for the next few seconds, so a wedged server costs the
        # first few requests their timeout instead of every request —
        # at kilo-rps an unsuspecting client burns timeout x rate worth
        # of in-flight capacity on a single SIGSTOP'd node
        self._suspect: dict[str, float] = {}

    def _ordered(self, locations: list[Location]) -> list[Location]:
        """Locations with currently-suspect replicas moved to the back
        (still tried — suspicion reorders, it never excludes)."""
        now = time.monotonic()
        fresh = [l for l in locations if self._suspect.get(l.url, 0.0) <= now]
        if len(fresh) == len(locations):
            return locations
        return fresh + [l for l in locations if l not in fresh]

    def _mark_suspect(self, netloc: str, for_s: float = 3.0) -> None:
        self._suspect[netloc] = time.monotonic() + for_s

    def _pooled_conn(self, netloc: str) -> http.client.HTTPConnection:
        conns = getattr(self._tl, "conns", None)
        if conns is None:
            conns = self._tl.conns = {}
        c = conns.get(netloc)
        if c is None:
            c = http.client.HTTPConnection(netloc, timeout=self.http_timeout)
            # Connect eagerly so we can disable Nagle: a reused keep-alive
            # socket otherwise serializes each small request behind the
            # server's ~40 ms delayed ACK.
            c.connect()
            c.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conns[netloc] = c
        return c

    def _drop_conn(self, netloc: str) -> None:
        conns = getattr(self._tl, "conns", None)
        if conns is not None:
            c = conns.pop(netloc, None)
            if c is not None:
                c.close()

    def _client_for(self, address: str) -> rpc.RpcClient:
        with self._lock:
            c = self._clients.get(address)
            if c is None:
                c = rpc.RpcClient(address)
                self._clients[address] = c
            return c

    def master_call(self, method: str, req: dict, timeout: float = 30.0) -> dict:
        """Unary master call with quorum failover + raft-leader redirect.

        Handles BOTH not-leader signals the master emits (the Assign-style
        `{"error": "not the raft leader", "leader": ...}` dict and the
        RpcFault FAILED_PRECONDITION used by the admin lock), so every
        component (clients, shell, sync tools) shares this one path."""
        import grpc as _grpc

        last_err: Optional[Exception] = None
        tried: list[str] = []
        candidates = [self._current] + [a for a in self.addresses if a != self._current]
        for addr in candidates:
            if addr in tried:
                continue
            tried.append(addr)
            try:
                resp = self._client_for(addr).call(
                    MASTER_SERVICE, method, req, timeout=timeout
                )
            except _grpc.RpcError as e:
                detail = e.details() or ""
                if (
                    e.code() == _grpc.StatusCode.FAILED_PRECONDITION
                    and "not the raft leader" in detail
                ):
                    # "…; leader is <addr>" when one is known; an election
                    # in flight says "no leader elected yet" — keep trying
                    leader = (
                        detail.rsplit("leader is ", 1)[1].strip()
                        if "leader is " in detail
                        else ""
                    )
                    if leader and leader not in tried:
                        candidates.append(leader)
                    last_err = e
                    continue
                if e.code() not in (
                    _grpc.StatusCode.UNAVAILABLE,
                    _grpc.StatusCode.DEADLINE_EXCEEDED,
                ):
                    raise  # app-level fault from a healthy master
                last_err = e
                continue
            if isinstance(resp, dict) and "not the raft leader" in str(
                resp.get("error", "")
            ):
                # an election may be in flight: a follower's hint can be
                # stale/empty — follow it if fresh, else keep trying
                leader = resp.get("leader") or ""
                if leader and leader not in tried:
                    candidates.append(leader)
                last_err = ClusterError(f"{addr}: not the raft leader")
                continue
            self._current = addr
            return resp
        raise ClusterError(f"no usable master ({tried}): {last_err}")

    def close(self) -> None:
        with self._lock:
            for c in self._clients.values():
                c.close()
            self._clients.clear()
        # only the calling thread's pooled sockets are reachable here;
        # other threads' daemon sockets close with the process
        conns = getattr(self._tl, "conns", None)
        if conns is not None:
            for c in conns.values():
                c.close()
            conns.clear()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- master RPCs ---------------------------------------------------------

    def assign(
        self,
        count: int = 1,
        collection: str = "",
        replication: str = "",
        ttl: str = "",
    ) -> AssignResponse:
        resp = AssignResponse.from_dict(
            self.master_call(
                "Assign",
                {
                    "count": count,
                    "collection": collection,
                    "replication": replication,
                    "ttl": ttl,
                },
            )
        )
        if resp.error:
            raise ClusterError(f"assign failed: {resp.error}")
        return resp

    def lookup(self, vid: int, refresh: bool = False) -> list[Location]:
        now = time.monotonic()
        with self._lock:
            hit = self._vid_cache.get(vid)
            if hit and not refresh and now - hit[0] < _VID_CACHE_TTL:
                return hit[1]
        resp = self.master_call("Lookup", {"volume_or_file_ids": [str(vid)]})
        entries = resp.get("volume_id_locations", [])
        locations = []
        if entries and not entries[0].get("error"):
            locations = [Location.from_dict(d) for d in entries[0]["locations"]]
        with self._lock:
            self._vid_cache[vid] = (now, locations)
        return locations

    def lookup_ec(self, vid: int) -> dict[int, list[Location]]:
        resp = self.master_call("LookupEcVolume", {"volume_id": vid})
        return {
            e["shard_id"]: [Location.from_dict(d) for d in e["locations"]]
            for e in resp.get("shard_id_locations", [])
        }

    def volume_list(self) -> dict:
        return self.master_call("VolumeList", {})

    def statistics(self) -> dict:
        return self.master_call("Statistics", {})

    # -- data ops (weed/operation analogs) ------------------------------------

    def upload(self, fid: str, data: bytes, mime: str = "", auth: str = "") -> int:
        """POST to the volume server owning fid's volume. `auth` is the
        JWT from Assign (required when the cluster runs secured)."""
        vid = int(fid.split(",", 1)[0])
        locations = self.lookup(vid)
        if not locations:
            raise ClusterError(f"no locations for volume {vid}")
        last_err: Optional[Exception] = None
        headers = _trace_headers()
        if mime:
            headers["Content-Type"] = mime
        if not auth and self.signing_key:
            auth = mint_file_token(self.signing_key, fid)
        if auth:
            headers["Authorization"] = "Bearer " + auth
        for loc in locations:
            try:
                req = urllib.request.Request(
                    f"{tls.scheme()}://{loc.url}/{fid}",
                    data=data,
                    method="POST",
                    headers=headers,
                )
                with tls.urlopen(req, timeout=self.http_timeout) as r:
                    r.read()
                    return len(data)
            except _FAILOVER_ERRORS as e:  # try a replica
                last_err = e
        raise ClusterError(f"upload of {fid} failed: {last_err}")

    def read(self, fid: str) -> bytes:
        return self.read_ex(fid)[0]

    def read_ex(self, fid: str) -> tuple[bytes, Optional[str]]:
        """Like read(), but also surfaces the serving class the volume
        server resolved the read to (X-Weedtpu-Read-Class: healthy /
        ec_intact / cached / degraded), or None when the server predates
        the header. Load harnesses use it to bucket per-request latency
        by what actually happened instead of guessing from topology."""
        vid = int(fid.split(",", 1)[0])
        last_err = None
        pooled = tls.scheme() == "http"
        # second pass refreshes the vid cache: the volume may have moved
        # (ec.encode cut-over, balance) since it was cached
        for attempt in range(2):
            locations = self.lookup(vid, refresh=attempt > 0)
            if not locations and attempt > 0:
                raise ClusterError(f"no locations for volume {vid}")
            headers = _trace_headers()
            if self.read_signing_key:
                headers["Authorization"] = "Bearer " + mint_file_token(
                    self.read_signing_key, fid
                )
            for loc in self._ordered(locations):
                if pooled:
                    # a kept-alive connection the server closed between
                    # requests surfaces as an error on the FIRST op: retry
                    # that once with a fresh connection before failing over
                    for _fresh in (False, True):
                        try:
                            c = self._pooled_conn(loc.url)
                            c.request("GET", "/" + fid, headers=headers)
                            r = c.getresponse()
                            body = r.read()
                        except _FAILOVER_ERRORS as e:
                            self._drop_conn(loc.url)
                            last_err = e
                            continue
                        if r.status == 200:
                            self._suspect.pop(loc.url, None)
                            return body, r.getheader(trace_mod.READ_CLASS_HEADER)
                        # 404 on one replica can be staleness (e.g. it was
                        # down during the write) — try the other replicas,
                        # but an answering server is not suspect
                        last_err = f"HTTP {r.status}"
                        break
                    else:
                        self._mark_suspect(loc.url)
                    continue
                try:
                    req = urllib.request.Request(f"{tls.scheme()}://{loc.url}/{fid}", headers=headers)
                    with tls.urlopen(req, timeout=self.http_timeout) as r:
                        body = r.read()
                        self._suspect.pop(loc.url, None)
                        return body, r.headers.get(trace_mod.READ_CLASS_HEADER)
                except urllib.error.HTTPError as e:
                    last_err = f"HTTP {e.code}"
                except _FAILOVER_ERRORS as e:
                    last_err = e
                    self._mark_suspect(loc.url)
        raise ClusterError(f"read of {fid} failed on all locations: {last_err}")

    def delete(self, fid: str) -> bool:
        vid = int(fid.split(",", 1)[0])
        ok = False
        headers = {}
        if self.signing_key:
            headers["Authorization"] = "Bearer " + mint_file_token(self.signing_key, fid)
        for loc in self.lookup(vid):
            try:
                req = urllib.request.Request(
                    f"{tls.scheme()}://{loc.url}/{fid}", method="DELETE", headers=headers
                )
                with tls.urlopen(req, timeout=self.http_timeout) as r:
                    r.read()
                    ok = True
            except _FAILOVER_ERRORS:
                continue
        return ok

    def submit(
        self, data: bytes, collection: str = "", replication: str = "",
        mime: str = "", ttl: str = "",
    ) -> SubmitResult:
        a = self.assign(collection=collection, replication=replication, ttl=ttl)
        size = self.upload(a.fid, data, mime=mime, auth=a.auth)
        return SubmitResult(fid=a.fid, url=a.url, size=size)
