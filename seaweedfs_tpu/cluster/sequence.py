"""Needle-id sequencers — mirror of weed/sequence [VERIFY: mount empty;
SURVEY.md §2.1 "Sequence" row]: a memory sequencer with optional durable
checkpointing (the reference persists via master metadata/raft; here a tiny
state file fsynced on batch boundaries), plus a snowflake sequencer for
coordination-free multi-master id allocation."""

from __future__ import annotations

import os
import threading
import time


class MemorySequencer:
    """Monotonic id allocator. With a state_path, the next-id watermark is
    persisted ahead of use in BATCH-sized leases so a crash never re-issues
    an id (the reference's raft-backed sequencer gives the same guarantee)."""

    BATCH = 10_000

    def __init__(self, start: int = 1, state_path: str | None = None):
        self._lock = threading.Lock()
        self._state_path = state_path
        self._next = start
        self._leased_until = start
        if state_path and os.path.exists(state_path):
            with open(state_path) as f:
                self._next = self._leased_until = int(f.read().strip() or start)

    def _lease(self, upto: int) -> None:
        if self._state_path:
            tmp = self._state_path + ".tmp"
            with open(tmp, "w") as f:
                f.write(str(upto))
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self._state_path)
        self._leased_until = upto

    def next_ids(self, count: int = 1) -> int:
        """Returns the first id of a contiguous run of `count`."""
        with self._lock:
            first = self._next
            end = first + count
            if end > self._leased_until:
                self._lease(end + self.BATCH)
            self._next = end
            return first

    @property
    def watermark(self) -> int:
        """Next id to be handed out (replicated to raft followers)."""
        with self._lock:
            return self._next

    def floor(self, value: int) -> None:
        """Never allocate below `value` again (applied from the raft
        leader's watermark; a new leader floors past it plus a margin)."""
        with self._lock:
            if value > self._next:
                self._next = value
                if value > self._leased_until:
                    self._lease(value + self.BATCH)


class SnowflakeSequencer:
    """41-bit ms timestamp | 10-bit node id | 12-bit sequence."""

    EPOCH_MS = 1_600_000_000_000

    def __init__(self, node_id: int):
        if not 0 <= node_id < 1024:
            raise ValueError("node_id must fit in 10 bits")
        self._node = node_id
        self._lock = threading.Lock()
        self._last_ms = 0
        self._seq = 0

    def next_ids(self, count: int = 1) -> int:
        """Snowflake ids are not contiguous, so batch assignment (count > 1,
        where the client derives fids by incrementing the key) only works
        with MemorySequencer; reject it here rather than hand out a run that
        collides with future allocations."""
        if count != 1:
            raise ValueError("SnowflakeSequencer cannot lease contiguous id runs")
        with self._lock:
            ms = time.time_ns() // 1_000_000
            if ms < self._last_ms:
                # clock stepped backwards (NTP): never reuse an old
                # timestamp — keep allocating in the last-seen millisecond
                ms = self._last_ms
            if ms == self._last_ms:
                self._seq += 1
                if self._seq >= 4096:
                    while ms <= self._last_ms:
                        ms = time.time_ns() // 1_000_000
                    self._seq = 0
            else:
                self._seq = 0
            self._last_ms = ms
            return ((ms - self.EPOCH_MS) << 22) | (self._node << 12) | self._seq
