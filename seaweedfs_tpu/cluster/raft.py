"""Raft leader election for master HA — mirror of the reference's master
quorum (weed/server/raft_server.go + raft_hashicorp.go; topology stays
soft state rebuilt from heartbeats, so the replicated hard state is
small) [VERIFY: mount empty; SURVEY.md §1 "N master processes (Raft
quorum)", §2.1 "Master" row].

What is replicated and why (matching the reference's design point that
volume-server heartbeats rebuild the topology on any master):

  - term / voted_for      — persisted per node (JSON), classic Raft safety
  - leader heartbeats     — carry a small `payload` dict (max volume id,
                            needle-sequence watermark) that followers
                            apply, so a new leader never reissues ids

This is election + watermark replication, not a general replicated log:
the reference keeps its cluster metadata the same way (soft topology +
raft-elected leader + tiny hard state), so a log machine would add
latency without adding safety here.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from typing import Callable, Optional

from seaweedfs_tpu import rpc

RAFT_SERVICE = "weedtpu.Raft"

FOLLOWER, CANDIDATE, LEADER = "follower", "candidate", "leader"


class RaftNode:
    def __init__(
        self,
        me: str,
        peers: list[str],
        server: rpc.RpcServer,
        state_dir: str = "",
        election_timeout: tuple[float, float] = (1.0, 2.0),
        payload_fn: Optional[Callable[[], dict]] = None,
        apply_fn: Optional[Callable[[dict], None]] = None,
        on_leader: Optional[Callable[[], None]] = None,
    ):
        self.me = me
        self.peers = [p for p in peers if p != me]
        self.state = FOLLOWER
        self.term = 0
        self.voted_for: Optional[str] = None
        self.leader: Optional[str] = None
        self._timeout_range = election_timeout
        self._payload_fn = payload_fn or (lambda: {})
        self._apply_fn = apply_fn or (lambda p: None)
        self._on_leader = on_leader
        self._lock = threading.RLock()
        self._last_heard = time.monotonic()
        self._last_quorum_ack = time.monotonic()
        self._stop = threading.Event()
        self._state_path = (
            os.path.join(state_dir, f"raft.{me.replace(':', '_')}.json")
            if state_dir
            else ""
        )
        self._load_state()
        svc = rpc.Service(RAFT_SERVICE)
        svc.add("RequestVote", self._rpc_request_vote)
        svc.add("AppendEntries", self._rpc_append_entries)
        server.add_service(svc)
        self._clients: dict[str, rpc.RpcClient] = {}
        self._clients_mu = threading.Lock()
        self._ticker = threading.Thread(target=self._run, daemon=True)

    # -- persistence ----------------------------------------------------------

    def _load_state(self) -> None:
        if self._state_path and os.path.exists(self._state_path):
            try:
                with open(self._state_path, encoding="utf-8") as f:
                    d = json.load(f)
                self.term = int(d.get("term", 0))
                self.voted_for = d.get("voted_for")
            except (ValueError, OSError):
                pass

    def _save_state(self) -> None:
        if not self._state_path:
            return
        tmp = self._state_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"term": self.term, "voted_for": self.voted_for}, f)
            f.flush()
            os.fsync(f.fileno())  # term/vote must survive a crash (election safety)
        os.replace(tmp, self._state_path)

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> None:
        if not self.peers:
            # single-node cluster: immediate leadership
            with self._lock:
                self.state = LEADER
                self.leader = self.me
            if self._on_leader:
                self._on_leader()
        self._ticker.start()

    def stop(self) -> None:
        self._stop.set()
        with self._clients_mu:
            clients, self._clients = list(self._clients.values()), {}
        for c in clients:
            c.close()

    @property
    def is_leader(self) -> bool:
        return self.state == LEADER

    def _client(self, peer: str) -> rpc.RpcClient:
        with self._clients_mu:
            c = self._clients.get(peer)
            if c is None:
                c = rpc.RpcClient(peer)
                self._clients[peer] = c
            return c

    # -- RPC handlers ---------------------------------------------------------

    def _rpc_request_vote(self, req: dict, ctx) -> dict:
        term, candidate = int(req["term"]), req["candidate"]
        with self._lock:
            if term > self.term:
                self.term = term
                self.voted_for = None
                self.state = FOLLOWER
                self._save_state()
            granted = term >= self.term and self.voted_for in (None, candidate)
            if granted:
                self.voted_for = candidate
                self._last_heard = time.monotonic()
                self._save_state()
        resp = {"term": self.term, "granted": granted}
        if granted:
            # piggyback this voter's applied payload: the winning candidate
            # adopts the freshest table from its vote quorum, which must
            # intersect any quorum that acked a replicated lease — so a
            # quorum-acked admin lock survives leader failover even though
            # this raft has no log up-to-dateness restriction
            resp["payload"] = self._payload_fn()
        return resp

    def _rpc_append_entries(self, req: dict, ctx) -> dict:
        term, leader = int(req["term"]), req["leader"]
        with self._lock:
            if term < self.term:
                return {"term": self.term, "ok": False}
            if term > self.term:
                self.term = term
                self.voted_for = None
                self.state = FOLLOWER
                self._save_state()
            elif self.state != FOLLOWER:
                # equal-term step-down (candidate lost the race): keep
                # voted_for — clearing it would allow a second vote in the
                # same term, breaking Raft's one-vote-per-term invariant
                self.state = FOLLOWER
                self._save_state()
            self.leader = leader
            self._last_heard = time.monotonic()
        payload = req.get("payload") or {}
        if payload:
            self._apply_fn(payload)
        return {"term": self.term, "ok": True}

    # -- main loop ------------------------------------------------------------

    def _election_deadline(self) -> float:
        lo, hi = self._timeout_range
        return random.uniform(lo, hi)

    def _run(self) -> None:
        deadline = self._election_deadline()
        while not self._stop.is_set():
            if self.state == LEADER:
                self._broadcast_heartbeat()
                # a leader partitioned from the quorum must step down, or
                # it keeps allocating ids that the majority-side leader
                # also allocates (split brain)
                if self.peers:
                    with self._lock:
                        silent = time.monotonic() - self._last_quorum_ack
                        if silent > self._timeout_range[1]:
                            self.state = FOLLOWER
                            self.leader = None
                self._stop.wait(self._timeout_range[0] / 3)
                continue
            self._stop.wait(0.05)
            with self._lock:
                waited = time.monotonic() - self._last_heard
            if waited >= deadline:
                self._campaign()
                deadline = self._election_deadline()

    def _campaign(self) -> None:
        with self._lock:
            self.state = CANDIDATE
            self.term += 1
            self.voted_for = self.me
            self._save_state()
            term = self.term
            self._last_heard = time.monotonic()
        resps = self._fanout("RequestVote", {"term": term, "candidate": self.me})
        votes = 1 + sum(1 for r in resps if r.get("granted"))
        # adopt voter payloads BEFORE taking leadership: apply_fn is
        # seq-aware, so the freshest lock table in the vote quorum wins
        # regardless of arrival order
        for r in resps:
            if r.get("granted") and r.get("payload"):
                try:
                    self._apply_fn(r["payload"])
                except Exception:  # noqa: BLE001 — a bad payload must not block election
                    pass
        higher = max((r["term"] for r in resps if r["term"] > term), default=0)
        quorum = (len(self.peers) + 1) // 2 + 1
        with self._lock:
            if higher > self.term:
                self.term = higher
                self.state = FOLLOWER
                self.voted_for = None
                self._save_state()
                return
            if self.state != CANDIDATE or self.term != term:
                return
            if votes >= quorum:
                self.state = LEADER
                self.leader = self.me
                self._last_quorum_ack = time.monotonic()
            else:
                self.state = FOLLOWER
                return
        self._broadcast_heartbeat()
        if self._on_leader:
            self._on_leader()

    def _peer_timeout(self) -> float:
        # well below the election floor: one dead peer must not stall the
        # round past a follower's deadline (leadership flapping)
        return max(0.2, self._timeout_range[0] / 4)

    def _fanout(self, method: str, req: dict) -> list[dict]:
        """Call all peers in PARALLEL; returns the responses received
        within the per-peer timeout."""
        results: list[dict] = []
        lock = threading.Lock()

        def one(peer: str) -> None:
            try:
                resp = self._client(peer).call(
                    RAFT_SERVICE, method, req, timeout=self._peer_timeout()
                )
            except Exception:  # noqa: BLE001 — unreachable peer
                return
            with lock:
                results.append(resp)

        threads = [threading.Thread(target=one, args=(p,)) for p in self.peers]
        for t in threads:
            t.start()
        for t in threads:
            t.join(self._peer_timeout() + 0.5)
        return results

    def _broadcast_heartbeat(self) -> bool:
        """One replication round. Returns True when a quorum acked."""
        with self._lock:
            term = self.term
        payload = self._payload_fn()
        resps = self._fanout(
            "AppendEntries", {"term": term, "leader": self.me, "payload": payload}
        )
        acks = sum(1 for r in resps if r.get("ok"))
        higher = max((r["term"] for r in resps if r["term"] > term), default=0)
        with self._lock:
            quorum = (len(self.peers) + 1) // 2 + 1
            quorum_ok = acks + 1 >= quorum
            if quorum_ok:
                self._last_quorum_ack = time.monotonic()
            if higher > self.term:
                self.term = higher
                self.state = FOLLOWER
                self.voted_for = None
                self._save_state()
                return False
        return quorum_ok

    def replicate_now(self) -> bool:
        """Synchronously push the current payload to a quorum (used by the
        master to make an admin-lock lease durable BEFORE handing the token
        to the client). Returns False when no quorum acked — the caller
        must treat the mutation as not committed."""
        if not self.peers:
            return self.is_leader  # single-node: local state is the quorum
        if not self.is_leader:
            return False
        return self._broadcast_heartbeat()
